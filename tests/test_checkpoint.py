"""Checkpoint / resume correctness.

Round-1 VERDICT: save worked but resume crashed (scalar opt-state leaves
restored onto device 0) and nothing tested CheckpointManager at all; ADVICE
flagged that resume also restarted the RNG stream and data iterator. The
test here is the strong form: an interrupted-and-resumed run must produce
EXACTLY the losses of an uninterrupted run — which only holds if (a) the
restored state matches bitwise, (b) per-step dropout keys are derived from
the step index, and (c) the data stream is fast-forwarded past
warmup + resumed steps.
"""

import numpy as np
import pytest

from dtc_tpu.train.trainer import train

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path, **kw):
    defaults = dict(
        steps=6,
        warmup_steps=2,
        log_every=1,
        output_dir=str(tmp_path / "out"),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    defaults.update(kw)
    cfg = train_cfg_factory("dp", **defaults)
    model_cfg = tiny_model_cfg.__class__(
        **{**tiny_model_cfg.__dict__, "dropout": 0.1}  # dropout ON: RNG matters
    )
    return cfg, model_cfg


def test_resume_matches_uninterrupted(train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path):
    import dataclasses

    cfg, model_cfg = _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path)

    # Uninterrupted 6-step run (checkpointing on, so stream/RNG identical).
    full = train(cfg, model_cfg, opt_cfg)
    assert len(full.losses) == 6

    # Interrupted run: 4 steps (checkpoints at 2 and 4)...
    cfg2 = dataclasses.replace(
        cfg,
        steps=4,
        output_dir=str(tmp_path / "out2"),
        checkpoint_dir=str(tmp_path / "ckpt2"),
    )
    train(cfg2, model_cfg, opt_cfg)

    # ...then resume to 6. Must replay steps 5-6 with identical losses.
    cfg3 = dataclasses.replace(cfg2, steps=6, output_dir=str(tmp_path / "out3"))
    resumed = train(cfg3, model_cfg, opt_cfg)
    assert len(resumed.losses) == 2
    np.testing.assert_allclose(resumed.losses, full.losses[4:6], rtol=1e-6)


def test_restore_gives_scalar_leaves_mesh_sharding(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """The round-1 failure mode: AdamW's scalar count leaves restored with
    SingleDeviceSharding crash the first donated train step after resume.
    Assert restore() places every leaf with a NamedSharding."""
    import jax
    from jax.sharding import NamedSharding

    from dtc_tpu.utils.checkpoint import CheckpointManager

    cfg, model_cfg = _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path, steps=2)
    result = train(cfg, model_cfg, opt_cfg)

    ckpt = CheckpointManager(cfg.checkpoint_dir)
    assert ckpt.latest_step() == 2
    restored = ckpt.restore(result.state)
    for path, leaf in jax.tree_util.tree_leaves_with_path(restored):
        if isinstance(leaf, jax.Array):
            assert isinstance(leaf.sharding, NamedSharding), (
                f"{jax.tree_util.keystr(path)} restored with {leaf.sharding}"
            )
    ckpt.close()
