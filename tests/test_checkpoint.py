"""Checkpoint / resume correctness.

Round-1 VERDICT: save worked but resume crashed (scalar opt-state leaves
restored onto device 0) and nothing tested CheckpointManager at all; ADVICE
flagged that resume also restarted the RNG stream and data iterator. The
test here is the strong form: an interrupted-and-resumed run must produce
EXACTLY the losses of an uninterrupted run — which only holds if (a) the
restored state matches bitwise, (b) per-step dropout keys are derived from
the step index, and (c) the data stream is fast-forwarded past
warmup + resumed steps.
"""

import numpy as np
import pytest

from dtc_tpu.train.trainer import train

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path, **kw):
    defaults = dict(
        steps=6,
        warmup_steps=2,
        log_every=1,
        output_dir=str(tmp_path / "out"),
        checkpoint_every=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    defaults.update(kw)
    cfg = train_cfg_factory("dp", **defaults)
    model_cfg = tiny_model_cfg.__class__(
        **{**tiny_model_cfg.__dict__, "dropout": 0.1}  # dropout ON: RNG matters
    )
    return cfg, model_cfg


def test_resume_matches_uninterrupted(train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path):
    import dataclasses

    cfg, model_cfg = _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path)

    # Uninterrupted 6-step run (checkpointing on, so stream/RNG identical).
    full = train(cfg, model_cfg, opt_cfg)
    assert len(full.losses) == 6

    # Interrupted run: 4 steps (checkpoints at 2 and 4)...
    cfg2 = dataclasses.replace(
        cfg,
        steps=4,
        output_dir=str(tmp_path / "out2"),
        checkpoint_dir=str(tmp_path / "ckpt2"),
    )
    train(cfg2, model_cfg, opt_cfg)

    # ...then resume to 6. Must replay steps 5-6 with identical losses.
    cfg3 = dataclasses.replace(cfg2, steps=6, output_dir=str(tmp_path / "out3"))
    resumed = train(cfg3, model_cfg, opt_cfg)
    assert len(resumed.losses) == 2
    np.testing.assert_allclose(resumed.losses, full.losses[4:6], rtol=1e-6)


def test_fresh_run_refuses_to_clobber_log(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """Round-4 VERDICT weak #1: a stray smoke run truncated the committed
    outputs/dp artifact. A fresh run into a dir with an existing log.csv
    must now refuse unless overwrite: true; resuming from a checkpoint
    into the same dir stays allowed without the flag."""
    import dataclasses

    cfg = train_cfg_factory("dp", steps=2, output_dir=str(tmp_path / "art"))
    train(cfg, tiny_model_cfg, opt_cfg)
    with pytest.raises(ValueError, match="refusing to overwrite"):
        train(cfg, tiny_model_cfg, opt_cfg)
    train(dataclasses.replace(cfg, overwrite=True), tiny_model_cfg, opt_cfg)

    # Resume path: checkpointed run, then MORE steps into the SAME dir.
    cfg2 = train_cfg_factory(
        "dp", steps=2, output_dir=str(tmp_path / "res"),
        checkpoint_every=2, checkpoint_dir=str(tmp_path / "res_ckpt"),
    )
    train(cfg2, tiny_model_cfg, opt_cfg)
    resumed = train(dataclasses.replace(cfg2, steps=4), tiny_model_cfg, opt_cfg)
    assert len(resumed.losses) == 2  # ran 3-4, no overwrite flag needed


def test_restore_gives_scalar_leaves_mesh_sharding(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """The round-1 failure mode: AdamW's scalar count leaves restored with
    SingleDeviceSharding crash the first donated train step after resume.
    Assert restore() places every leaf with a NamedSharding."""
    import jax
    from jax.sharding import NamedSharding

    from dtc_tpu.utils.checkpoint import CheckpointManager

    cfg, model_cfg = _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path, steps=2)
    result = train(cfg, model_cfg, opt_cfg)

    ckpt = CheckpointManager(cfg.checkpoint_dir)
    assert ckpt.latest_step() == 2
    restored = ckpt.restore(result.state)
    for path, leaf in jax.tree_util.tree_leaves_with_path(restored):
        if isinstance(leaf, jax.Array):
            assert isinstance(leaf.sharding, NamedSharding), (
                f"{jax.tree_util.keystr(path)} restored with {leaf.sharding}"
            )
    ckpt.close()


def test_fineweb_resume_seeks_via_sidecar(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path, monkeypatch
):
    """dataset=fineweb resume must SEEK (checkpointed stream position) —
    no drain loop, no re-consumption of used documents — and replay the
    identical losses. Wires make_host_iterator to an injected document
    list; the resumed construction gets a guarded tail-only view."""
    import dataclasses

    from dtc_tpu.data.fineweb import FinewebStream
    from dtc_tpu.train import trainer as trainer_mod
    from tests.test_data import _TailOnlySeq, _docs

    seq = tiny_model_cfg.max_seq_len + 1
    docs = _docs(n=900, tokens=50)
    calls = []

    def fake_host_iterator(train_cfg, model_cfg, skip_batches=0,
                           seed_offset=0, stream_position=None, history=64,
                           **kw):
        calls.append(stream_position)
        source = docs
        if stream_position is not None:
            source = _TailOnlySeq(docs, stream_position["docs_consumed"])
        it = FinewebStream(
            train_cfg.batch, seq, documents=source, position=stream_position,
            history=history,
        )
        for _ in range(skip_batches):
            next(it)
        return it

    monkeypatch.setattr(trainer_mod, "make_host_iterator", fake_host_iterator)

    cfg, model_cfg = _cfgs(
        train_cfg_factory, tiny_model_cfg, tmp_path, dataset="fineweb"
    )
    full = train(cfg, model_cfg, opt_cfg)

    cfg2 = dataclasses.replace(
        cfg, steps=4,
        output_dir=str(tmp_path / "out2"), checkpoint_dir=str(tmp_path / "ckpt2"),
    )
    train(cfg2, model_cfg, opt_cfg)
    cfg3 = dataclasses.replace(cfg2, steps=6, output_dir=str(tmp_path / "out3"))
    resumed = train(cfg3, model_cfg, opt_cfg)

    np.testing.assert_allclose(resumed.losses, full.losses[4:6], rtol=1e-6)
    # The resumed run was constructed FROM a position (seek), not a drain.
    assert calls[-1] is not None and calls[-1]["docs_consumed"] > 0


def test_sigterm_checkpoints_flushes_and_stops(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """SURVEY §5 failure detection: SIGTERM mid-run must stop the loop,
    save a final checkpoint at the interrupt step, and flush the CSV —
    for ANY run, not just scripts/resume_demo.py. The signal fires
    deterministically from inside the data iterator (no timing flake)."""
    import os
    import signal

    from dtc_tpu.data.synthetic import synthetic_batch_iterator
    from dtc_tpu.utils.checkpoint import CheckpointManager

    cfg, model_cfg = _cfgs(
        train_cfg_factory, tiny_model_cfg, tmp_path, steps=50, warmup_steps=0,
        checkpoint_every=1000,  # only the SIGTERM path saves
    )

    def signaling_batches():
        it = synthetic_batch_iterator(cfg.batch, model_cfg.max_seq_len + 1, 97)
        for i, b in enumerate(it):
            if i == 7:
                os.kill(os.getpid(), signal.SIGTERM)
            yield b

    handler_before = signal.getsignal(signal.SIGTERM)
    res = train(cfg, model_cfg, opt_cfg, host_iterator=signaling_batches())
    done = len(res.losses)
    assert 0 < done < 50, "run should stop early on SIGTERM"

    mgr = CheckpointManager(cfg.checkpoint_dir)
    assert mgr.latest_step() == done, "final checkpoint at the interrupt step"
    mgr.close()
    with open(os.path.join(cfg.output_dir, "log.csv")) as f:
        rows = f.read().strip().splitlines()
    assert len(rows) == done + 1, "all rows flushed (header + one per step)"
    # The handler is restored: a later SIGTERM must not be swallowed by the
    # trainer's (now-dead) handler.
    assert signal.getsignal(signal.SIGTERM) is handler_before


def test_fineweb_resume_with_holdout_eval(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path, monkeypatch
):
    """Seek-resume composed with the held-out eval split: the resumed run
    must keep withholding not-yet-passed holdout batches from training
    (identical losses to the uninterrupted run) and rebuild the same eval
    set from the stream head."""
    import dataclasses

    from dtc_tpu.data.fineweb import FinewebStream
    from dtc_tpu.train import trainer as trainer_mod
    from tests.test_data import _docs

    seq = tiny_model_cfg.max_seq_len + 1
    docs = _docs(n=2000, tokens=50)

    def fake_host_iterator(train_cfg, model_cfg, skip_batches=0,
                           seed_offset=0, stream_position=None, history=64,
                           **kw):
        it = FinewebStream(
            train_cfg.batch, seq, documents=docs, position=stream_position,
            history=history,
        )
        for _ in range(skip_batches):
            next(it)
        return it

    monkeypatch.setattr(trainer_mod, "make_host_iterator", fake_host_iterator)
    kw = dict(dataset="fineweb", eval_every=3, eval_batches=2,
              eval_holdout_every=4)
    cfg, model_cfg = _cfgs(train_cfg_factory, tiny_model_cfg, tmp_path, **kw)
    full = train(cfg, model_cfg, opt_cfg)

    cfg2 = dataclasses.replace(
        cfg, steps=2,
        output_dir=str(tmp_path / "out2"), checkpoint_dir=str(tmp_path / "ckpt2"),
    )
    train(cfg2, model_cfg, opt_cfg)
    cfg3 = dataclasses.replace(cfg2, steps=6, output_dir=str(tmp_path / "out3"))
    resumed = train(cfg3, model_cfg, opt_cfg)

    np.testing.assert_allclose(resumed.losses, full.losses[2:6], rtol=1e-6)
    # Same held-out eval set -> same eval losses at the shared steps.
    full_evals = dict(full.eval_losses)
    for step, loss in resumed.eval_losses:
        np.testing.assert_allclose(loss, full_evals[step], rtol=1e-6)
