"""Mesh resolution: every shipped config must be runnable (or fail loudly)
on canonical slice sizes, and the DCN/hybrid branch must construct.

Round-2 VERDICT "What's weak" #6: the shipped PP config auto-resolved to
pipe=8 on a v5e-8 and assert-crashed on 12 % 8 != 0 deep in the pipeline
step. Resolution is now layer-aware; these tests pin that contract for all
configs x device counts.
"""

import glob
import math
import os

import jax
import pytest

from dtc_tpu.config.loader import load_config
from dtc_tpu.parallel.mesh import build_mesh, resolve_mesh_shape

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(REPO, "configs", "train_config_*.yaml")))


@pytest.mark.parametrize("config_path", CONFIGS, ids=os.path.basename)
@pytest.mark.parametrize("num_devices", [1, 2, 4, 8])
def test_shipped_configs_resolve_or_raise_cleanly(config_path, num_devices):
    train_cfg, model_cfg, _ = load_config(config_path)
    try:
        shape = resolve_mesh_shape(
            train_cfg.parallel, num_devices, train_cfg.mesh, n_layers=model_cfg.n_layers
        )
    except ValueError:
        # A clear config-level error (e.g. the 3d config's explicit 2x2x2
        # mesh on 4 devices) is acceptable; an AssertionError deep in the
        # pipeline step is not.
        return
    pipe, data, model_ax = shape
    assert pipe * data * model_ax == num_devices
    assert model_cfg.n_layers % pipe == 0, (
        f"{os.path.basename(config_path)} on {num_devices} devices resolved to "
        f"pipe={pipe}, which does not divide n_layers={model_cfg.n_layers}"
    )


def test_pp_auto_absorbs_indivisible_devices_into_data():
    """8 devices, 12 layers: auto-pp caps pipe at 4 (largest divisor of both)
    and gives the leftover factor 2 to data parallelism."""
    from dtc_tpu.config.schema import MeshConfig

    shape = resolve_mesh_shape("pp", 8, MeshConfig(), n_layers=12)
    assert shape == (4, 2, 1)


def test_explicit_indivisible_pipe_raises_value_error():
    from dtc_tpu.config.schema import MeshConfig

    with pytest.raises(ValueError, match="n_layers"):
        resolve_mesh_shape("pp", 8, MeshConfig(pipe=8), n_layers=12)


def test_hybrid_dcn_mesh_constructs():
    """DCN factors multiply into the axis: ICI (1,2,2) x DCN (2,1,1) over 8
    virtual devices gives a (pipe=2, data=2, model=2) mesh whose pipe axis
    spans the (slow) inter-slice dimension."""
    mesh = build_mesh((1, 2, 2), devices=jax.devices(), dcn_shape=(2, 1, 1))
    assert dict(mesh.shape) == {"pipe": 2, "data": 2, "model": 2}
    assert math.prod(mesh.devices.shape) == 8


def test_mesh_from_config_applies_dcn_factors():
    from dtc_tpu.config.schema import MeshConfig

    from dtc_tpu.parallel.mesh import mesh_from_config

    mesh = mesh_from_config(
        "dp", MeshConfig(model=2, dcn_data=2), n_layers=12
    )
    # 8 devices / dcn 2 = 4 ICI devices; model=2 explicit, dp absorbs 2;
    # total data axis = ici 2 x dcn 2 = 4.
    assert dict(mesh.shape) == {"pipe": 1, "data": 4, "model": 2}
