"""Flash-attention parity vs the dense reference implementation.

Runs the Pallas kernels in interpreter mode on CPU (conftest forces the CPU
platform); the same code compiles via Mosaic on TPU. Parity target:
``dense_causal_attention`` (ops/attention.py), which itself reproduces the
reference semantics (`/root/reference/model/CausalSelfAttention.py:34-42`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtc_tpu.ops.attention import causal_attention, dense_causal_attention
from dtc_tpu.ops.flash_attention import flash_causal_attention, supports

# Interpret-mode kernel suite: minutes on a 1-core host. `pytest -m quick`
# skips it; tier-1 (`-m 'not slow'`) still runs it.
pytestmark = pytest.mark.kernels


def _qkv(key, b, t, h, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


# (T, D, block_q, block_kv): flagship-like padded head_dim, lane-sized head
# dim, and multi-block tilings exercising the online-softmax accumulation.
SHAPES = [
    (256, 32, 256, 256),    # single block, padded head_dim (flagship-like)
    (256, 128, 128, 128),   # 2x2 blocks, lane-width head_dim
    (512, 32, 128, 128),    # 4x4 blocks, padded head_dim (flagship tiling)
    (512, 64, 256, 128),    # rectangular blocks
]


@pytest.mark.parametrize("t,d,bq,bkv", SHAPES)
def test_forward_parity(t, d, bq, bkv):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, t, 3, d)
    ref = dense_causal_attention(q, k, v)
    got = flash_causal_attention(q, k, v, block_q=bq, block_kv=bkv)
    assert got.shape == ref.shape and got.dtype == ref.dtype
    assert jnp.max(jnp.abs(got - ref)) < 2e-5


@pytest.mark.parametrize("t,d,bq,bkv", SHAPES)
def test_grad_parity(t, d, bq, bkv):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, t, 2, d)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, block_q=bq, block_kv=bkv) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_got):
        err = jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-8)
        assert err < 2e-4, f"d{name} relative error {err}"


def test_bf16_forward():
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 256, 2, 32, jnp.bfloat16)
    ref = dense_causal_attention(q, k, v)
    got = flash_causal_attention(q, k, v, block_q=128, block_kv=128)
    assert got.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; compare in fp32 with a loose tolerance.
    assert jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32))) < 0.05


def test_supports_flagship():
    # The flagship (head_dim=32, T=512) must qualify — VERDICT round 1 flagged
    # the old d % 128 == 0 heuristic as unreachable for it.
    assert supports(512, 32, 512, 512)
    assert supports(512, 32, 128, 128)
    assert not supports(100, 32, 128, 128)  # T not tileable


def test_dispatch_unknown_impl():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 2, 16)
    with pytest.raises(ValueError):
        causal_attention(q, k, v, impl="nope")


# ---- packed transpose-free path (single tile, heads grouped into lanes) ----

PACKED_CASES = [
    # (t, d, h): g = 128//d heads per lane group; h % g == 0 engages packing
    (256, 32, 8),
    (512, 32, 16),   # the flagship shape exactly
    (256, 64, 4),
    (256, 128, 2),   # g=1: packed degenerates to per-head lane blocks
]


@pytest.mark.parametrize("t,d,h", PACKED_CASES)
def test_packed_forward_parity(t, d, h):
    from dtc_tpu.ops.flash_attention import _packed_group

    assert _packed_group(d, h) is not None  # the case actually packs
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, t, h, d)
    got = flash_causal_attention(q, k, v, block_q=t, block_kv=t)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("t,d,h", [(256, 32, 8), (256, 64, 4)])
def test_packed_grad_parity(t, d, h):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, t, h, d)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, block_q=t, block_kv=t) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_dense, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-4,
                                   err_msg=f"d{name}")


def test_packed_group_predicate():
    """The dispatcher packs exactly when 128 % head_dim == 0 and the group
    divides the head count."""
    from dtc_tpu.ops.flash_attention import _packed_group

    assert _packed_group(32, 8) == 4
    assert _packed_group(32, 3) is None
    assert _packed_group(64, 4) == 2
    assert _packed_group(128, 2) == 1
    assert _packed_group(256, 4) is None  # head_dim wider than the lane block


def test_packed_single_matches_packed_multi():
    """Same shape through both packed kernels: block_q = t engages the
    one-pass single-tile path, block_q = t // 2 the online-softmax
    causal-block-skipping path. Outputs agree to fp32 accumulation noise."""
    q, k, v = _qkv(jax.random.PRNGKey(5), 2, 256, 8, 32)
    single = flash_causal_attention(q, k, v, block_q=256, block_kv=256)
    multi = flash_causal_attention(q, k, v, block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(single), np.asarray(multi), atol=2e-5)


@pytest.mark.parametrize("bq,bkv", [(128, 128), (256, 256), (128, 256)])
def test_packed_multi_tile_parity(bq, bkv):
    """Packed multi-tile (online softmax + causal block skip) vs dense."""
    t, d, h = 512, 32, 8
    q, k, v = _qkv(jax.random.PRNGKey(6), 2, t, h, d)
    got = flash_causal_attention(q, k, v, block_q=bq, block_kv=bkv)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_packed_multi_tile_grad_parity():
    t, d, h = 256, 32, 8
    q, k, v = _qkv(jax.random.PRNGKey(7), 2, t, h, d)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, block_q=128, block_kv=128) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_dense, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-4,
                                   err_msg=f"d{name}")


def test_bwd_tiling_override_is_semantically_invisible():
    """attention_block_{q,kv}_bwd retile the backward only — gradients
    must match the default tiling to fp32 accumulation noise, and the
    knob must refuse the non-packed fallback loudly (it would silently
    run the forward tiling there)."""
    t, d, h = 256, 32, 8
    q, k, v = _qkv(jax.random.PRNGKey(9), 2, t, h, d)

    def loss(bqb, bkvb):
        return jax.grad(
            lambda q, k, v: jnp.sum(flash_causal_attention(
                q, k, v, block_q=64, block_kv=128,
                block_q_bwd=bqb, block_kv_bwd=bkvb,
            ) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)

    g_default = loss(0, 0)
    g_retiled = loss(128, 256)
    for name, a, b in zip("qkv", g_default, g_retiled):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-5,
                                   err_msg=f"d{name}")

    # Non-packed fallback (head_dim 48: 128 % 48 != 0) must reject the knob.
    q3, k3, v3 = _qkv(jax.random.PRNGKey(10), 1, 256, 2, 48)
    with pytest.raises(ValueError, match="packed flash path"):
        flash_causal_attention(q3, k3, v3, block_q=128, block_kv=128,
                               block_kv_bwd=256)


def test_packed_split_bwd_grad_parity(monkeypatch):
    """The long-context backward (T > _PACKED_MAX_T routes to the split
    dq/dkv kernels with O(block) scratch). Shrink the threshold so the
    split path runs at a CPU-interpretable shape, and pin it against
    dense autodiff AND the fused packed backward."""
    import dtc_tpu.ops.flash_attention as fa

    t, d, h = 256, 32, 8
    q, k, v = _qkv(jax.random.PRNGKey(8), 2, t, h, d)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, block_q=64, block_kv=128) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    g_fused = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(fa, "_PACKED_MAX_T", 128)  # force the split backward
    g_split = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, ref, got in zip("qkv", g_dense, g_split):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4,
                                   err_msg=f"d{name} split vs dense")
    for name, a, b in zip("qkv", g_fused, g_split):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-5,
                                   err_msg=f"d{name} split vs fused")


def test_split_bwd_kernels_route_through_causal_block_dispatch(monkeypatch):
    """Round-5 VERDICT #3: the causal block skip (above-diagonal tiles
    predicated out entirely, diagonal-straddling tiles the only ones
    paying the VPU mask pass) landed via ``_causal_block_dispatch`` in
    the fused packed kernels — assert the SPLIT dq/dkv pair routes
    through the same dispatcher, so the T=8192 path gets the same 25%+
    compute skip the ceiling analysis (PERF.md round 7) credits it with.
    The spy records at kernel-trace time: a rewrite of either split
    kernel that drops the dispatcher (reverting to an always-on mask, or
    no predication at all) goes red here; the NUMERICS of the skip are
    pinned by test_packed_split_bwd_grad_parity above."""
    import dtc_tpu.ops.flash_attention as fa

    seen = []
    orig = fa._causal_block_dispatch

    def spy(i, j, block_q, block_kv, accumulate):
        seen.append(accumulate.__qualname__)
        return orig(i, j, block_q, block_kv, accumulate)

    monkeypatch.setattr(fa, "_causal_block_dispatch", spy)
    t, d, h = 256, 32, 8
    g = fa._packed_group(d, h)
    b, hd = 1, h * d
    q = jnp.zeros((b, t, hd), jnp.float32)
    do = out = q
    lse = jnp.zeros((b, hd // 128, t, g), jnp.float32)
    # Tracing the split backward traces both kernel bodies (no execution
    # needed — make_jaxpr is enough for the spy to see the call sites).
    jax.make_jaxpr(
        lambda q, k, v, do, out, lse: fa._packed_split_bwd_call(
            q, k, v, do, out, lse, 64, 128, g, d, 1.0
        )
    )(q, q, q, do, out, lse)
    owners = {name.split(".")[0] for name in seen}
    assert "_dq_kernel_packed" in owners, seen
    assert "_dkv_kernel_packed" in owners, seen


def test_whole_t_tiles_past_packed_max_t_raise(monkeypatch):
    """Guard-order regression (round-5 ADVICE): a tiling override that
    resolves to one whole-T tile past _PACKED_MAX_T must be a clear
    ValueError at the API surface — previously the single-tile fast path
    was checked FIRST, so the fused kernel's full-T VMEM scratches hit an
    opaque Mosaic compile OOM on TPU. Threshold shrunk so the guard fires
    at a CPU-testable shape."""
    import functools

    import dtc_tpu.ops.flash_attention as fa

    t, d, h = 256, 32, 8
    q, k, v = _qkv(jax.random.PRNGKey(9), 1, t, h, d)
    monkeypatch.setattr(fa, "_PACKED_MAX_T", 128)

    # Forward tiling resolves to one whole-T tile.
    with pytest.raises(ValueError, match="whole-T"):
        flash_causal_attention(q, k, v, block_q=t, block_kv=t)
    # Forward tiled fine, but the BACKWARD override is whole-T.
    with pytest.raises(ValueError, match="whole-T"):
        flash_causal_attention(q, k, v, block_q=128, block_kv=128,
                               block_q_bwd=t, block_kv_bwd=t)
    # Defense inside the vjp rule itself (direct _flash_packed callers
    # bypass the API validation): same clear error, not a kernel launch.
    g = fa._packed_group(d, h)
    pk = lambda x: x.reshape(1, t, h * d)
    lse = jnp.zeros((1, h * d // fa._LANES, t, g), jnp.float32)
    with pytest.raises(ValueError, match="whole-T"):
        fa._packed_flash_bwd(
            t, t, g, d, float(d ** -0.5), 0, 0,
            (pk(q), pk(k), pk(v), pk(q), lse), pk(q),
        )
    # Multi-tile tilings still route to the split backward and train.
    out = flash_causal_attention(q, k, v, block_q=128, block_kv=128)
    assert out.shape == q.shape
