"""Dev-config sanitizers (SURVEY §5 race/assert tooling analog).

The reference stack's debugging story is device-side asserts + sanitizer
builds; the TPU-native analogs are ``jax_debug_nans`` (re-run jitted
computations whose outputs contain NaN and raise at the producing
primitive) and ``checkify`` guards on traced invariants that cannot raise
at trace time. Both are opt-in config fields, off in perf runs.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import checkify

from dtc_tpu.generate import init_cache
from dtc_tpu.models.gpt import GPT
from dtc_tpu.train.trainer import train


def test_debug_nans_raises_instead_of_garbage(
    train_cfg_factory, tiny_model_cfg, opt_cfg
):
    """lr=NaN poisons params on the first update; with the knob on, the
    next step raises FloatingPointError instead of logging NaN losses."""
    bad_opt = dataclasses.replace(opt_cfg, lr=float("nan"))

    # Baseline failure mode: silently trains on garbage.
    cfg = train_cfg_factory("dp", steps=2)
    res = train(cfg, tiny_model_cfg, bad_opt)
    assert not jnp.isfinite(jnp.asarray(res.losses[-1]))

    with pytest.raises(FloatingPointError):
        train(
            dataclasses.replace(cfg, debug_nans=True),
            tiny_model_cfg, bad_opt,
        )
    # The knob must not leak into later runs in the same process.
    assert jax.config.jax_debug_nans is False


def test_debug_checks_catch_decode_cache_overflow(tiny_model_cfg):
    """models/gpt.py decode caller contract: total decoded length must stay
    <= max_seq_len, else dynamic_update_slice clamps and corrupts logits
    silently. With debug_checks, a checkified apply raises instead."""
    cfg = dataclasses.replace(tiny_model_cfg, max_seq_len=8, debug_checks=True)
    model = GPT(cfg)
    x = jnp.ones((1, 4), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)["params"]
    cache = init_cache(model, 1)

    def prefill(cache, toks):
        return model.apply(
            {"params": params, "cache": cache}, toks,
            train=False, decode=True, mutable=["cache"],
        )

    checked = checkify.checkify(prefill)
    # Within bound: 6 of 8 positions — no error.
    err, (_, mut) = checked(cache, jnp.ones((1, 6), jnp.int32))
    err.throw()
    # Overflow: frontier 6 + 4 tokens > 8 — must raise, not clamp.
    err, _ = checked(mut["cache"], jnp.ones((1, 4), jnp.int32))
    with pytest.raises(checkify.JaxRuntimeError, match="decode cache overflow"):
        err.throw()
