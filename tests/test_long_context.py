"""Long-context demonstration: the capability ring attention exists for.

Round-2 VERDICT "What's weak" #8: ring attention was only ever tested at
T=64. Here it runs at T=8192 over a model=8 ring — a sequence whose dense
O(T²) fp32 score tensor ALONE (32 GiB at flagship batch/heads) exceeds a
v5e chip's 16 GiB HBM — and matches the dense oracle computed on the host
(where 125 GB of RAM makes the oracle feasible).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtc_tpu.config.schema import MeshConfig
from dtc_tpu.ops.attention import dense_causal_attention
from dtc_tpu.ops.ring_attention import ring_causal_attention
from dtc_tpu.parallel.mesh import mesh_from_config

# Interpret-mode kernel suite: minutes on a 1-core host. `pytest -m quick`
# skips it; tier-1 (`-m 'not slow'`) still runs it.
pytestmark = pytest.mark.kernels

T_LONG = 8192


def test_ring_attention_t8192_matches_dense():
    mesh = mesh_from_config("3d", MeshConfig(pipe=1, data=1, model=8))
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    # b=1, h=1, d=16 keeps the CPU oracle tractable (one (8192, 8192) fp32
    # score matrix); the ring path's per-device working set is what the
    # test is about, not model scale.
    q, k, v = (jax.random.normal(kk, (1, T_LONG, 1, 16), jnp.float32) for kk in ks)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_causal_attention(q, k, v))(q, k, v)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=3e-5)


def test_ring_memory_scales_with_ring_size():
    """The arithmetic the op exists for: at the flagship's batch/heads and
    T=8192 (the length the parity test above demonstrates), dense causal
    attention's fp32 score tensor ALONE exceeds a v5e chip's 16 GiB HBM —
    before the saved softmax weights, params, optimizer, or activations.
    The ring's per-device, per-step score block is ring² smaller and fits
    trivially."""
    b, h = 8, 16
    t = T_LONG
    ring = 8
    hbm_bytes = 16 * 2**30                      # v5e HBM
    dense_scores = b * h * t * t * 4            # fp32 (B,H,T,T)
    assert dense_scores > hbm_bytes, f"{dense_scores / 2**30:.1f} GiB"
    t_loc = t // ring
    ring_scores = b * h * t_loc * t_loc * 4     # fp32 (B,H,T/r,T/r) per device
    assert ring_scores * 2 < hbm_bytes // 8      # fits with room for the model
    assert ring_scores == dense_scores // ring**2


def test_ring_composes_with_data_parallelism_at_length():
    """T=2048 over model=4 composed with data=2 (the 3D-mesh composition the
    trainer actually uses for long-context runs)."""
    mesh = mesh_from_config("3d", MeshConfig(pipe=1, data=2, model=4))
    key = jax.random.PRNGKey(1)
    ks = jax.random.split(key, 3)
    q, k, v = (jax.random.normal(kk, (2, 2048, 2, 16), jnp.float32) for kk in ks)
    with mesh:
        got = jax.jit(lambda q, k, v: ring_causal_attention(q, k, v))(q, k, v)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=3e-5)
