"""Fault-tolerance subsystem (ISSUE 2): chaos harness, anomaly-guard policy
ladder, verified checkpoints with intact-step fallback, self-healing data
streams, prefetch error propagation, watchdog, coordinator timeout.

The flagship test injects the full kill chain into one short offline run —
a transient stream fault, a corrupted latest checkpoint, and a NaN loss —
and asserts the run completes every step with losses IDENTICAL to an
uninjected run: recovery must be invisible in the training trajectory.
"""

import dataclasses
import glob
import json
import os
import time

import numpy as np
import pytest

from dtc_tpu.config.schema import (
    ChaosConfig,
    GuardConfig,
    ResilienceConfig,
    StreamRetryConfig,
    WatchdogConfig,
)
from dtc_tpu.train.trainer import train

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


# ---------------------------------------------------------------------------
# retry wrapper


def test_resilient_iterator_heals_at_exact_position():
    from dtc_tpu.resilience.retry import resilient_iterator

    docs = list(range(20))
    opens = []
    armed = {"on": True}

    def factory(index):
        opens.append(index)

        def gen():
            for off, v in enumerate(docs[index:]):
                if armed["on"] and index + off == 7:
                    armed["on"] = False
                    raise ConnectionError("flaky shard")
                yield v

        return gen()

    out = list(
        resilient_iterator(factory, backoff_s=0.0, jitter=0.0, sleep=lambda s: None)
    )
    assert out == docs, "exactly-once: no item dropped or replayed"
    assert opens == [0, 7], "re-opened at the exact failure index"


def test_resilient_iterator_exhausts_to_typed_error():
    from dtc_tpu.resilience import DataStreamError
    from dtc_tpu.resilience.retry import resilient_iterator

    sleeps = []

    def factory(index):
        raise ConnectionError("network down")

    it = resilient_iterator(
        factory, max_attempts=3, backoff_s=1.0, backoff_max_s=10.0,
        jitter=0.0, sleep=sleeps.append,
    )
    with pytest.raises(DataStreamError, match="3 consecutive attempts") as ei:
        next(it)
    assert isinstance(ei.value.__cause__, ConnectionError)
    assert sleeps == [1.0, 2.0], "exponential backoff, attempts-1 sleeps"


def test_resilient_iterator_cancel_interrupts_backoff():
    import threading

    from dtc_tpu.resilience.retry import resilient_iterator

    cancel = threading.Event()

    def factory(index):
        raise ConnectionError("down")

    it = resilient_iterator(
        factory, max_attempts=5, backoff_s=3600.0, jitter=0.0, cancel=cancel
    )
    cancel.set()
    assert list(it) == [], "cancelled stream ends immediately, no backoff sleep"


# ---------------------------------------------------------------------------
# anomaly guard ladder


def test_guard_ladder_rollback_then_abort():
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(GuardConfig(max_rollbacks=1), can_rollback=True)
    assert g.check_window(1, [1.0, 0.9]).action == "ok"
    d = g.check_window(2, [float("nan"), 0.8])
    assert d.action == "rollback" and "non-finite" in d.reason
    g.note_rollback()
    assert g.check_window(3, [float("inf")]).action == "abort"


def test_guard_without_checkpoint_only_warns():
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(GuardConfig(), can_rollback=False)
    assert g.check_window(1, [float("nan")]).action == "warn"


def test_guard_spike_detection_vs_trailing_median():
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(GuardConfig(spike_factor=3.0), can_rollback=True)
    for s in range(1, 6):
        assert g.check_window(s, [1.0, 1.1]).action == "ok"
    d = g.check_window(6, [10.0, 11.0])
    assert d.action == "rollback" and "spike" in d.reason


def test_guard_tolerates_when_updates_skipped_device_side():
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(
        GuardConfig(skip_nonfinite_updates=True, max_consecutive_skips=2),
        can_rollback=True,
    )
    assert g.check_window(1, [float("nan")]).action == "tolerate"
    assert g.check_window(2, [float("nan")]).action == "tolerate"
    assert g.check_window(3, [float("nan")]).action == "rollback"
    # a healthy window resets the consecutive-skip budget
    g.note_rollback()
    assert g.check_window(4, [1.0]).action == "ok"
    assert g.check_window(5, [float("nan")]).action == "tolerate"


def test_guard_forgiveness_resets_budget_after_clean_streak():
    """Regression (ISSUE 15 satellite): two WELL-SEPARATED transients on
    a long run must both be rollback-able when clean_steps_to_forgive is
    set — max_rollbacks bounds rollbacks per incident, not per run
    lifetime (a week-long run used to die on its Nth transient)."""
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(
        GuardConfig(max_rollbacks=1, clean_steps_to_forgive=3),
        can_rollback=True,
    )
    # Incident 1: NaN -> the one budgeted rollback.
    assert g.check_window(1, [float("nan")]).action == "rollback"
    g.note_rollback()
    # Three consecutive healthy windows forgive the incident...
    for s in (2, 3, 4):
        assert g.check_window(s, [1.0, 0.9]).action == "ok"
    # ...so incident 2 (well-separated NaN) rolls back again, no abort.
    assert g.check_window(5, [float("nan")]).action == "rollback"
    g.note_rollback()
    # An anomaly RESETS the clean streak: two healthy windows are not
    # enough, the next anomaly inside the un-forgiven window aborts.
    assert g.check_window(6, [1.0]).action == "ok"
    assert g.check_window(7, [1.0]).action == "ok"
    assert g.check_window(8, [float("inf")]).action == "abort"

    # Legacy lifetime budget (forgive=0): the second transient aborts
    # even after an arbitrarily long clean streak.
    g0 = AnomalyGuard(GuardConfig(max_rollbacks=1), can_rollback=True)
    assert g0.check_window(1, [float("nan")]).action == "rollback"
    g0.note_rollback()
    for s in range(2, 12):
        assert g0.check_window(s, [1.0]).action == "ok"
    assert g0.check_window(12, [float("nan")]).action == "abort"


def test_guard_healthy_loss_rejects_finite_spike():
    from dtc_tpu.resilience import AnomalyGuard

    g = AnomalyGuard(GuardConfig(spike_factor=3.0), can_rollback=True)
    for s in range(1, 6):
        g.check_window(s, [1.0, 1.1])
    assert g.healthy_loss(1.2)
    assert not g.healthy_loss(10.0), "finite spike must not be checkpointed"
    assert not g.healthy_loss(float("nan"))


# ---------------------------------------------------------------------------
# watchdog


def test_watchdog_flags_outlier_without_poisoning_median():
    from dtc_tpu.resilience import StepWatchdog

    wd = StepWatchdog(WatchdogConfig(enabled=True, factor=5.0, min_samples=3))
    for s in range(1, 6):
        assert wd.observe(s, 0.1) is None
    flag = wd.observe(6, 1.0)
    assert flag is not None and flag["step"] == 6 and flag["factor"] >= 5.0
    # the outlier is excluded from the trailing median
    assert wd.observe(7, 0.1) is None and wd.flags == 1


def test_watchdog_hard_timeout_interrupts_main():
    from dtc_tpu.resilience import StepWatchdog

    hits = []
    wd = StepWatchdog(
        WatchdogConfig(enabled=True, hard_timeout_s=0.05),
        interrupt=lambda: hits.append(1),
    )
    wd.start()
    wd.arm(step=1)
    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    wd.stop()
    assert hits and wd.timed_out


# ---------------------------------------------------------------------------
# prefetch error paths (satellite: original exception, never a silent hang)


def _mesh_and_spec():
    from jax.sharding import PartitionSpec as P

    from dtc_tpu.parallel.mesh import build_mesh

    return build_mesh((1, 8, 1)), P("data", None)


def test_prefetch_worker_exception_reaches_consumer_as_original():
    from dtc_tpu.data.prefetch import ShardedPrefetchIterator

    class TokenizerBoom(RuntimeError):
        pass

    def source():
        yield np.zeros((8, 9), np.int32)
        raise TokenizerBoom("bad document")

    mesh, spec = _mesh_and_spec()
    pre = ShardedPrefetchIterator(source(), mesh, spec, queue_size=2)
    next(pre)
    with pytest.raises(TokenizerBoom, match="bad document"):
        next(pre)


def test_prefetch_dead_worker_raises_typed_error_not_hang(monkeypatch):
    from dtc_tpu.data.prefetch import ShardedPrefetchIterator
    from dtc_tpu.resilience import DataStreamError

    # A worker that dies WITHOUT delivering its error sentinel (C-level
    # crash analog): the consumer must get a typed error via the liveness
    # check, not block on queue.get forever.
    monkeypatch.setattr(ShardedPrefetchIterator, "_worker", lambda self: None)
    monkeypatch.setattr(ShardedPrefetchIterator, "_POLL_S", 0.05)
    mesh, spec = _mesh_and_spec()
    pre = ShardedPrefetchIterator(iter([]), mesh, spec, queue_size=1)
    with pytest.raises(DataStreamError, match="died without"):
        next(pre)


def test_prefetch_close_stops_worker_thread():
    from dtc_tpu.data.prefetch import ShardedPrefetchIterator

    def endless():
        while True:
            yield np.zeros((8, 9), np.int32)

    mesh, spec = _mesh_and_spec()
    pre = ShardedPrefetchIterator(endless(), mesh, spec, queue_size=1)
    next(pre)
    pre.close()
    assert not pre._thread.is_alive(), "close() must reap the worker"
    pre.close()  # idempotent


# ---------------------------------------------------------------------------
# verified checkpoints + atomic sidecars


def _mini_state(v: float):
    import jax.numpy as jnp

    return {"params": {"w": jnp.full((4, 4), float(v), jnp.float32)},
            "count": jnp.asarray(int(v), jnp.int32)}


def _corrupt_largest_file(root: str) -> str:
    target, size = None, -1
    for dirpath, _, names in os.walk(root):
        for name in names:
            p = os.path.join(dirpath, name)
            s = os.path.getsize(p)
            if s > size:
                target, size = p, s
    with open(target, "r+b") as f:
        f.truncate(size // 2)
    return target


def test_checkpoint_manifest_written_and_verified(tmp_path):
    from dtc_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, _mini_state(2))
    manifest = json.load(open(tmp_path / "manifest_2.json"))
    assert manifest["step"] == 2 and manifest["files"], "non-empty manifest"
    assert mgr.verify_step(2)
    mgr.close()


def test_corrupt_latest_falls_back_to_intact_step(tmp_path):
    from dtc_tpu.utils.checkpoint import CheckpointManager

    events = []
    mgr = CheckpointManager(
        str(tmp_path), on_event=lambda e, **f: events.append((e, f))
    )
    mgr.save(2, _mini_state(2))
    mgr.save(4, _mini_state(4))
    assert mgr.latest_step() == 4
    _corrupt_largest_file(mgr.step_dir(4))
    assert not mgr.verify_step(4)
    assert mgr.latest_step() == 2, "latest_step skips the corrupt step"
    restored, step = mgr.restore_latest(_mini_state(0))
    assert step == 2
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]), 2.0)
    assert any(
        e == "recovery" and f["action"] == "ckpt_fallback" for e, f in events
    ), "fallback must be reported for telemetry"
    mgr.close()


def test_save_overwrites_stale_step_after_rollback(tmp_path):
    from dtc_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, _mini_state(2))
    _corrupt_largest_file(mgr.step_dir(2))
    mgr.save(2, _mini_state(7))  # replay past a rollback re-saves the step
    assert mgr.verify_step(2) and mgr.latest_step() == 2
    restored, _ = mgr.restore_latest(_mini_state(0))
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]), 7.0)
    mgr.close()


def test_checkpoint_keep_n_gc_prunes_verified_older_steps(tmp_path):
    """Retention (ISSUE 15 satellite): keep_n bounds the step count —
    older steps (and their manifests) are garbage-collected after each
    verified save, so long runs no longer accumulate unboundedly. GC only
    ever runs AFTER the newer step verified, so the newest keep_n steps
    always include an intact restore target."""
    from dtc_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    for s in (2, 4, 6, 8):
        mgr.save(s, _mini_state(s))
    assert mgr.all_steps() == [6, 8]
    manifests = sorted(glob.glob(str(tmp_path / "manifest_*.json")))
    assert [os.path.basename(m) for m in manifests] == [
        "manifest_6.json", "manifest_8.json"
    ], "manifest sidecars pruned with their steps"
    assert not os.path.isdir(mgr.step_dir(2))
    # Fallback still works inside the retained window.
    _corrupt_largest_file(mgr.step_dir(8))
    restored, step = mgr.restore_latest(_mini_state(0))
    assert step == 6
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]), 6.0)
    mgr.close()
    with pytest.raises(ValueError, match="keep_n"):
        CheckpointManager(str(tmp_path / "bad"), keep_n=0)


def test_checkpoint_replay_resave_below_stale_latest_survives_gc(tmp_path):
    """A resumed run that fell back past corrupt steps re-saves steps
    numerically BELOW the stale latest during replay. Orbax's own
    max_to_keep retention used to reap that fresh out-of-order save the
    moment it landed (leaving an empty manifest that blessed a vanished
    step); retention is ours now, and the just-saved step is never the
    GC victim — even at keep_n=1 with a stale later step on disk."""
    from dtc_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), keep_n=3)
    for s in (10, 20, 30, 40):
        mgr.save(s, _mini_state(s))
    mgr.close()

    # Resume-world: a fresh manager replays past a rollback to 20 and
    # re-saves 30 while stale 40 is still the on-disk latest.
    mgr2 = CheckpointManager(str(tmp_path), keep_n=3)
    mgr2.save(30, _mini_state(77))
    assert os.path.isdir(mgr2.step_dir(30)), "fresh re-save reaped"
    assert mgr2.verify_step(30)
    assert sorted(mgr2.all_steps()) == [20, 30, 40]
    mgr2.close()

    # keep_n=1 + a stale LATER step: "newest keep_n" alone would delete
    # the just-saved recovery point and leave only the stale step.
    root1 = tmp_path / "k1"
    m = CheckpointManager(str(root1), keep_n=1)
    for s in (10, 20):
        m.save(s, _mini_state(s))
    m.close()
    m = CheckpointManager(str(root1), keep_n=1)
    m.save(10, _mini_state(5))  # rollback-to-start replay save
    assert os.path.isdir(m.step_dir(10)), "current save must survive GC"
    assert os.path.exists(
        str(root1 / "manifest_10.json")
    ), "manifest pruning must exempt the just-saved step too (verify_step "
    "TRUSTS a manifest-less step — silent integrity stripping otherwise)"
    assert m.verify_step(10)
    m.close()


def test_sidecars_atomic_and_tolerant_of_torn_files(tmp_path):
    from dtc_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), verify=False)
    mgr.save_stream(2, {"position": {"docs_consumed": 5, "buffer": [1, 2]},
                        "stream_index": 3}, 0)
    assert not glob.glob(str(tmp_path / "*.tmp")), "no temp litter"
    assert mgr.load_stream(2, 0)["stream_index"] == 3
    # a torn (pre-atomic-era) sidecar degrades to the drain fallback
    (tmp_path / "stream_4_p0.json").write_text('{"position": {"docs')
    assert mgr.load_stream(4, 0) is None
    # eval-set npz: round-trip + torn-file tolerance
    batches = [np.arange(6, dtype=np.int32).reshape(2, 3)]
    mgr.save_eval_set(batches, 0)
    assert not glob.glob(str(tmp_path / "*.tmp"))
    np.testing.assert_array_equal(mgr.load_eval_set(0)[0], batches[0])
    (tmp_path / "eval_set_p1.npz").write_bytes(b"not an npz")
    assert mgr.load_eval_set(1) is None
    mgr.close()


# ---------------------------------------------------------------------------
# coordinator-init timeout (satellite)


def test_coordinator_timeout_plumbed_env_beats_config(monkeypatch):
    import jax

    import dtc_tpu.utils.dist as dist

    calls = {}

    class FakeDistributed:
        def initialize(self, **kw):
            calls.update(kw or {"<none>": True})
            raise Exception("coordinator unreachable")

    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(jax, "distributed", FakeDistributed())
    monkeypatch.setenv(dist.TIMEOUT_ENV, "7")
    with pytest.raises(RuntimeError, match="coordinator"):
        dist.maybe_initialize_distributed(True, 99)
    assert calls == {"initialization_timeout": 7}, "env knob wins over config"

    calls.clear()
    monkeypatch.setenv(dist.TIMEOUT_ENV, "0")  # 0 = restore jax's default
    with pytest.raises(RuntimeError):
        dist.maybe_initialize_distributed(True, 99)
    assert calls == {"<none>": True}, "env 0 means jax default, not timeout=0"

    calls.clear()
    monkeypatch.delenv(dist.TIMEOUT_ENV)
    with pytest.raises(RuntimeError, match="Common causes"):
        dist.maybe_initialize_distributed(True, 99)
    assert calls == {"initialization_timeout": 99}, "config value plumbed"


# ---------------------------------------------------------------------------
# config plumbing


def test_resilience_yaml_block_loads_typed(tmp_path):
    from dtc_tpu.config.loader import load_yaml_dataclass
    from dtc_tpu.config.schema import TrainConfig

    p = tmp_path / "t.yaml"
    p.write_text(
        "seed: 0\nparallel: dp\nbatch: 8\nsteps: 2\nlog_every: 1\n"
        "output_dir: ''\n"
        "resilience:\n"
        "  guard: {spike_factor: 2.5, max_rollbacks: 1}\n"
        "  watchdog: {enabled: true, factor: 4.0}\n"
        "  stream_retry: {max_attempts: 2, backoff_s: 0.5}\n"
        "  chaos: {enabled: true, nan_at_step: 3}\n"
    )
    cfg = load_yaml_dataclass(p, TrainConfig)
    assert cfg.resilience.guard.spike_factor == 2.5
    assert cfg.resilience.watchdog.enabled and cfg.resilience.watchdog.factor == 4.0
    assert cfg.resilience.stream_retry.max_attempts == 2
    assert cfg.resilience.chaos.enabled and cfg.resilience.chaos.nan_at_step == 3


def test_chaos_config_validates():
    with pytest.raises(ValueError, match="corrupt_mode"):
        ChaosConfig(corrupt_mode="scribble")
    with pytest.raises(ValueError, match="factor"):
        WatchdogConfig(factor=0.5)


# ---------------------------------------------------------------------------
# end-to-end chaos runs (the acceptance scenario)


def _dropout_model(tiny_model_cfg):
    # dropout ON so the rollback replay also proves RNG-stream re-seek.
    return tiny_model_cfg.__class__(
        **{**tiny_model_cfg.__dict__, "dropout": 0.1}
    )


def _fineweb_fake(monkeypatch, docs, seq):
    """Route make_host_iterator to an injected offline document list,
    passing the trainer's chaos/retry wiring through — the exact path a
    network FinewebStream takes, minus the network."""
    from dtc_tpu.data.fineweb import FinewebStream
    from dtc_tpu.train import trainer as trainer_mod

    def fake(train_cfg, model_cfg, skip_batches=0, seed_offset=0,
             stream_position=None, history=64, chaos=None, on_recovery=None,
             cancel=None):
        it = FinewebStream(
            train_cfg.batch, seq, documents=docs, position=stream_position,
            history=history, retry=train_cfg.resilience.stream_retry,
            chaos=chaos, on_recovery=on_recovery, cancel=cancel,
        )
        for _ in range(skip_batches):
            next(it)
        return it

    monkeypatch.setattr(trainer_mod, "make_host_iterator", fake)


def _read_events(output_dir):
    path = os.path.join(output_dir, "obs", "events.r0.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_chaos_end_to_end_recovery(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path, monkeypatch
):
    """Acceptance: inject (a) a transient fineweb stream error, (b) a
    corrupted latest checkpoint, (c) a NaN loss at step 5 into one short
    offline run. The run must complete all steps, record the recovery
    events, end with a finite loss, and — because the rollback restores a
    verified checkpoint and re-seeks the stream — produce losses IDENTICAL
    to an uninjected run."""
    from tests.test_data import _docs

    model_cfg = _dropout_model(tiny_model_cfg)
    seq = model_cfg.max_seq_len + 1
    _fineweb_fake(monkeypatch, _docs(n=3000, tokens=50), seq)

    base = dict(
        steps=8, warmup_steps=2, log_every=1, dataset="fineweb",
        checkpoint_every=2,
    )
    clean_cfg = train_cfg_factory(
        "dp", output_dir=str(tmp_path / "clean"),
        checkpoint_dir=str(tmp_path / "clean_ckpt"), **base,
    )
    clean = train(clean_cfg, model_cfg, opt_cfg)
    assert len(clean.losses) == 8

    res = ResilienceConfig(
        stream_retry=StreamRetryConfig(backoff_s=0.0, jitter=0.0),
        chaos=ChaosConfig(
            enabled=True,
            data_error_at_doc=30,    # mid-run transient stream fault
            corrupt_ckpt_at_step=4,  # latest checkpoint at rollback time
            nan_at_step=5,           # poisons params+loss after step 5
        ),
    )
    chaos_cfg = dataclasses.replace(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "chaos"),
            checkpoint_dir=str(tmp_path / "chaos_ckpt"), **base,
        ),
        resilience=res,
    )
    chaotic = train(chaos_cfg, model_cfg, opt_cfg)

    # The run completed every step and recovered to a finite loss.
    assert len(chaotic.losses) == 8
    assert np.isfinite(chaotic.losses[-1])
    # Stream re-seek parity: the post-rollback trajectory (and therefore the
    # WHOLE loss list) matches the uninjected run bit-for-bit.
    np.testing.assert_allclose(chaotic.losses, clean.losses, rtol=1e-6)

    events = _read_events(chaos_cfg.output_dir)
    kinds = {e["kind"] for e in events if e["etype"] == "chaos"}
    assert kinds == {"data_error", "ckpt_corrupt", "nan_loss"}
    actions = [e["action"] for e in events if e["etype"] == "recovery"]
    assert actions.count("stream_retry") == 1, actions
    assert actions.count("rollback") == 1, actions
    assert actions.count("ckpt_fallback") >= 1, actions
    rb = next(e for e in events if e["etype"] == "recovery"
              and e["action"] == "rollback")
    assert rb["to_step"] == 2, "corrupt step 4 skipped, intact step 2 used"
    assert any(e["etype"] == "anomaly" for e in events)


def test_nan_rollback_synthetic_matches_clean(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """Rollback on the synthetic (seeded O(1)-seek) data path: NaN at step 3
    -> rollback to the step-2 checkpoint -> replay matches the clean run."""
    model_cfg = _dropout_model(tiny_model_cfg)
    base = dict(steps=6, warmup_steps=2, log_every=1, checkpoint_every=2)
    clean = train(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "clean"),
            checkpoint_dir=str(tmp_path / "clean_ckpt"), **base,
        ),
        model_cfg, opt_cfg,
    )
    chaos_cfg = dataclasses.replace(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "chaos"),
            checkpoint_dir=str(tmp_path / "chaos_ckpt"), **base,
        ),
        resilience=ResilienceConfig(
            chaos=ChaosConfig(enabled=True, nan_at_step=3)
        ),
    )
    chaotic = train(chaos_cfg, model_cfg, opt_cfg)
    assert len(chaotic.losses) == 6
    np.testing.assert_allclose(chaotic.losses, clean.losses, rtol=1e-6)


def test_rollback_commits_window_prefix_when_boundaries_misalign(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """checkpoint_every NOT a multiple of log_every: the rollback target
    (step 4) sits INSIDE the detection window (4..6, last boundary 3). The
    window's healthy prefix (step 4) must still be committed — no silently
    dropped steps, losses identical to the clean run."""
    model_cfg = _dropout_model(tiny_model_cfg)
    base = dict(steps=6, warmup_steps=2, log_every=3, checkpoint_every=2)
    clean = train(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "clean"),
            checkpoint_dir=str(tmp_path / "clean_ckpt"), **base,
        ),
        model_cfg, opt_cfg,
    )
    chaotic = train(
        dataclasses.replace(
            train_cfg_factory(
                "dp", output_dir=str(tmp_path / "chaos"),
                checkpoint_dir=str(tmp_path / "chaos_ckpt"), **base,
            ),
            resilience=ResilienceConfig(
                chaos=ChaosConfig(enabled=True, nan_at_step=5)
            ),
        ),
        model_cfg, opt_cfg,
    )
    assert len(chaotic.losses) == len(clean.losses) == 6
    np.testing.assert_allclose(chaotic.losses, clean.losses, rtol=1e-6)
    events = _read_events(str(tmp_path / "chaos"))
    rb = next(e for e in events if e["etype"] == "recovery"
              and e["action"] == "rollback")
    assert rb["to_step"] == 4


def test_poisoned_checkpoint_is_never_saved(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path
):
    """checkpoint_every=1 with NaN onset BEFORE the next log boundary: the
    save at the poisoned step must be SKIPPED (a bit-intact NaN checkpoint
    would become the rollback target and trap the ladder), so the rollback
    lands on the last healthy step and the run still matches clean."""
    model_cfg = _dropout_model(tiny_model_cfg)
    base = dict(steps=6, warmup_steps=2, log_every=3, checkpoint_every=1)
    clean = train(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "clean"),
            checkpoint_dir=str(tmp_path / "clean_ckpt"), **base,
        ),
        model_cfg, opt_cfg,
    )
    chaotic = train(
        dataclasses.replace(
            train_cfg_factory(
                "dp", output_dir=str(tmp_path / "chaos"),
                checkpoint_dir=str(tmp_path / "chaos_ckpt"), **base,
            ),
            resilience=ResilienceConfig(
                chaos=ChaosConfig(enabled=True, nan_at_step=2)
            ),
        ),
        model_cfg, opt_cfg,
    )
    assert len(chaotic.losses) == 6
    np.testing.assert_allclose(chaotic.losses, clean.losses, rtol=1e-6)
    events = _read_events(str(tmp_path / "chaos"))
    actions = [e["action"] for e in events if e["etype"] == "recovery"]
    # saves at poisoned steps 2 and 3 skipped; rollback restores healthy 1
    assert "skip_checkpoint" in actions
    rb = next(e for e in events if e["etype"] == "recovery"
              and e["action"] == "rollback")
    assert rb["to_step"] == 1


def test_chaos_sigterm_checkpoints_sidecar_and_resumes_bit_exact(
    train_cfg_factory, tiny_model_cfg, opt_cfg, tmp_path, monkeypatch
):
    """Satellite: the SIGTERM graceful-stop path via the chaos harness's
    simulated preemption — checkpoint + stream sidecar written at the stop
    step, CSV flushed, and a resume=True rerun continues bit-exactly."""
    from dtc_tpu.utils.checkpoint import CheckpointManager
    from tests.test_data import _docs

    model_cfg = _dropout_model(tiny_model_cfg)
    seq = model_cfg.max_seq_len + 1
    _fineweb_fake(monkeypatch, _docs(n=2000, tokens=50), seq)

    base = dict(
        steps=6, warmup_steps=2, log_every=1, dataset="fineweb",
        checkpoint_every=1000,  # only the SIGTERM path saves
    )
    full = train(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "full"),
            checkpoint_dir=str(tmp_path / "full_ckpt"), **base,
        ),
        model_cfg, opt_cfg,
    )

    pre_cfg = dataclasses.replace(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "pre"),
            checkpoint_dir=str(tmp_path / "pre_ckpt"), **base,
        ),
        resilience=ResilienceConfig(
            chaos=ChaosConfig(enabled=True, sigterm_at_step=3)
        ),
    )
    pre = train(pre_cfg, model_cfg, opt_cfg)
    assert len(pre.losses) == 3, "stopped at the simulated preemption"

    mgr = CheckpointManager(pre_cfg.checkpoint_dir)
    assert mgr.latest_step() == 3, "checkpoint written at the stop step"
    assert mgr.load_stream(3, 0) is not None, "stream sidecar written"
    mgr.close()
    with open(os.path.join(pre_cfg.output_dir, "log.csv")) as f:
        assert len(f.read().strip().splitlines()) == 4, "CSV flushed (hdr+3)"
    events = _read_events(pre_cfg.output_dir)
    assert any(
        e["etype"] == "chaos" and e["kind"] == "sigterm" for e in events
    )

    resumed = train(
        dataclasses.replace(
            pre_cfg, output_dir=str(tmp_path / "res"),
            resilience=ResilienceConfig(),  # chaos off for the rerun
        ),
        model_cfg, opt_cfg,
    )
    assert len(resumed.losses) == 3
    np.testing.assert_allclose(resumed.losses, full.losses[3:6], rtol=1e-6)
