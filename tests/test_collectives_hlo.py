"""Compiled-HLO collective assertions (round-5 VERDICT #4).

Loss-parity tests prove the parallel steps compute the right numbers;
these prove they compute them the intended WAY: each strategy's step is
lowered/compiled on the 8-virtual-device CPU mesh THROUGH THE SHARED
ANALYSIS ENGINE (``dtc_tpu.analysis.lowering.compiled_train_hlo`` — the
same trainer-faithful lowering the graph auditor baselines, so these
one-off assertions and the permanent audit cannot drift apart) and the
optimized module is searched for the collectives the design requires —
and for the ones it must NOT contain. A partitioner regression that
silently falls back to replicate-and-slice (correct numbers, catastrophic
memory/comm) fails here, not on a future TPU bill.

Backend note: XLA's CPU pipeline DECOMPOSES reduce-scatter into
all-reduce + partition-id-indexed dynamic-slice, so the FSDP assertion
accepts either the literal instruction or that fingerprint; all-to-all
and all-gather survive as first-class instructions.
"""

import dataclasses
import re

import pytest

from dtc_tpu.analysis.hlo import (
    all_gather_shapes,
    collective_counts,
    has_partition_id,
)
from dtc_tpu.analysis.lowering import compiled_train_hlo
from dtc_tpu.config.schema import MeshConfig
from dtc_tpu.parallel.sharding import DEFAULT_RULES, FSDP_RULES, ring_rules_from


def test_ulysses_step_emits_all_to_all(tiny_model_cfg, opt_cfg):
    """Ulysses = all-to-all head<->seq reshards inside attention. If the
    all-to-alls vanish, the partitioner fell back to gathering the full
    sequence — numerically identical, defeats the whole scheme."""
    cfg = dataclasses.replace(tiny_model_cfg, attention="ulysses")
    txt = compiled_train_hlo(
        "3d", MeshConfig(pipe=1, data=2, model=4), cfg, opt_cfg,
        ring_rules_from(DEFAULT_RULES),
    )
    c = collective_counts(txt)
    assert c["all-to-all"] > 0, f"ulysses lost its all-to-alls: {dict(c)}"


@pytest.mark.parametrize("dispatch", ["einsum", "sort"])
def test_ep_moe_step_emits_all_to_all(tiny_model_cfg, opt_cfg, dispatch):
    """EP comes from two rule-table rows (experts/experts_p -> "model");
    the partitioner must turn them into token<->expert all-to-alls for
    BOTH dispatch backends — the sort path's scatter/gather formulation
    must not silently replicate the expert computation."""
    cfg = dataclasses.replace(
        tiny_model_cfg, moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
        moe_dispatch=dispatch,
    )
    txt = compiled_train_hlo(
        "3d", MeshConfig(pipe=1, data=4, model=2), cfg, opt_cfg, DEFAULT_RULES
    )
    c = collective_counts(txt)
    assert c["all-to-all"] > 0, f"EP[{dispatch}] lost its all-to-alls: {dict(c)}"
    # The expert FFN einsums must contract EP-locally: a (B,T,E,cap)- or
    # (B,E,cap,ff)-shaped ALL-GATHER would mean the partitioner gathered
    # the full expert dimension to every device. E is sharded 4->2 per
    # device here, so any gather landing a full leading-E rank-4 f32
    # tensor is the replicate-everything fallback.
    e, b = 4, 8
    bad = [
        s for s in all_gather_shapes(txt)
        if re.match(rf"f32\[{b},{e},", s) or re.match(rf"f32\[{b},\d+,{e},", s)
    ]
    assert not bad, f"EP[{dispatch}] gathered full expert tensors: {bad}"


def test_fsdp_step_all_gathers_and_reduce_scatters(tiny_model_cfg, opt_cfg):
    """ZeRO-3: parameters all-gather at use, gradients land as shards.
    The reduce-scatter may appear decomposed (all-reduce + partition-id
    dynamic-slice) on the CPU backend — accept either form, but demand
    the partition-id fingerprint so a plain replicated all-reduce (DP,
    not ZeRO) cannot pass."""
    txt = compiled_train_hlo("fsdp", MeshConfig(), tiny_model_cfg, opt_cfg, FSDP_RULES)
    c = collective_counts(txt)
    assert c["all-gather"] > 0, f"FSDP lost its param all-gathers: {dict(c)}"
    assert c["reduce-scatter"] > 0 or (
        c["all-reduce"] > 0 and has_partition_id(txt)
    ), f"FSDP lost its gradient reduce-scatter (or decomposition): {dict(c)}"
    # Forbidden: a FULL stacked-parameter all-gather outside the layer
    # scan. Inside the scan each layer's (d, d_ff)-class kernel gathers
    # per-layer (rank 2); a rank-3 gather with the stacked n_layers=4
    # leading axis means XLA hoisted the whole parameter out of the scan
    # and the ZeRO memory win is gone.
    L = tiny_model_cfg.n_layers
    stacked = [s for s in all_gather_shapes(txt) if re.match(rf"f32\[{L},\d+,\d+\]", s)]
    assert not stacked, f"full stacked-param all-gathers outside the scan: {stacked}"
