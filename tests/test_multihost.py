"""Real 2-process multi-host training on CPU (round-2 VERDICT item 2c).

Two OS processes, 4 virtual CPU devices each, joined into one 8-device JAX
distributed runtime via a local coordinator (gloo CPU collectives). Each
process feeds its own half of the global batch through
``jax.make_array_from_process_local_data``; the test asserts

- both processes compute IDENTICAL losses (the gradient all-reduce really
  spans processes — independent training would diverge immediately because
  the processes feed different data),
- the loss differs from a run where both processes feed process-0's data
  (i.e. the per-process streams actually contribute distinct batches),
- only process 0 writes log.csv (lead-only logging).

The reference has no multi-process anything (SURVEY.md §2.2 "Multi-host").
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

coord, pid, variant = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()       # 2 x 4 virtual
assert jax.local_device_count() == 4
dup = variant == "dup"

from dtc_tpu.config.schema import MeshConfig, ModelConfig, OptimConfig, TrainConfig
from dtc_tpu.train.trainer import make_host_iterator, train

model_cfg = ModelConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_ff=128,
    max_seq_len=32, dropout=0.0, param_dtype="float32",
    compute_dtype="float32", attention="dense",
)
opt_cfg = OptimConfig(lr=1e-3, weight_decay=0.1, grad_clip=1.0)
# "tp_in_host": the canonical pod layout — tensor parallelism over each
# process's local devices (fast links), data parallelism across processes
# (slow links, one gradient all-reduce per step).
mesh = MeshConfig(model=4, data=2) if variant == "tp_in_host" else MeshConfig()
train_cfg = TrainConfig(
    seed=0, parallel="tp" if variant == "tp_in_host" else "dp",
    batch=8, steps=3, log_every=1,
    output_dir=os.environ["DTC_OUT"], dataset="synthetic",
    warmup_steps=0, prefetch=0, mesh=mesh,
)

host_it = None
if dup:
    # Negative control: both processes feed process-0's stream.
    from dtc_tpu.data.synthetic import synthetic_batch_iterator
    host_it = synthetic_batch_iterator(4, 33, 97, seed=0)

res = train(train_cfg, model_cfg, opt_cfg, host_iterator=host_it)
print("LOSSES", json.dumps([pid, res.losses]))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, variant: str):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            .replace("--xla_force_host_platform_device_count=8", "")
            + " --xla_force_host_platform_device_count=4"
            + " --xla_cpu_use_thunk_runtime=false"
        )
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["DTC_OUT"] = str(tmp_path / f"variant_{variant}")
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER, coord, str(pid), variant],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        if p.returncode != 0:
            pytest.fail(f"worker rc={p.returncode}\nstdout:{out[-2000:]}\nstderr:{err[-2000:]}")
        outs.append(out)
    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("LOSSES"):
                pid, vals = json.loads(line.split(" ", 1)[1])
                losses[pid] = vals
    return losses


def test_two_process_training(tmp_path):
    losses = _launch(tmp_path, "dp")
    assert set(losses) == {0, 1}
    # Cross-process gradient sync: both processes see the same global loss.
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    assert len(losses[0]) == 3 and all(np.isfinite(losses[0]))

    # Lead-only logging: process 0 wrote the CSV; nothing from process 1.
    out_dir = tmp_path / "variant_dp"
    rows = (out_dir / "log.csv").read_text().strip().splitlines()
    assert len(rows) == 4  # header + 3 steps

    # Distinct per-process data: duplicating process-0's stream on both
    # hosts changes the global batch, hence the losses.
    dup_losses = _launch(tmp_path, "dup")
    np.testing.assert_allclose(dup_losses[0], dup_losses[1], rtol=1e-6)
    assert not np.allclose(losses[0], dup_losses[0], rtol=1e-4), (
        "per-process streams look identical — striding/offsets not applied"
    )


def test_two_process_tp_within_host_dp_across(tmp_path):
    """The canonical pod layout: a (data=2, model=4) mesh where tensor
    parallelism stays on each process's local devices and data parallelism
    crosses the process boundary. Exercises cross-process GSPMD collectives
    beyond the plain gradient all-reduce (activations replicated across
    hosts, per-layer TP all-reduces local)."""
    losses = _launch(tmp_path, "tp_in_host")
    assert set(losses) == {0, 1}
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    assert len(losses[0]) == 3 and all(np.isfinite(losses[0]))
