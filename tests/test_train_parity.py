"""Cross-strategy loss parity — the framework's core correctness property.

The reference validates DP≡TP≡PP only by eyeballing overlaid loss curves
(`/root/reference/README.md:51`, SURVEY.md §4). Here it is a test: from
identical init params and identical batches, every strategy must produce
the same losses and the same updated params to numerical tolerance.
"""

import jax
import numpy as np

from dtc_tpu.config.schema import MeshConfig
from dtc_tpu.train.trainer import train
from tests.conftest import make_train_cfg


def run(parallel, tiny_model_cfg, opt_cfg, steps=4, **kw):
    cfg = make_train_cfg(parallel, steps=steps, **kw)
    res = train(cfg, tiny_model_cfg, opt_cfg)
    return res


def test_dp_equals_tp_losses(tiny_model_cfg, opt_cfg):
    r_dp = run("dp", tiny_model_cfg, opt_cfg)
    r_tp = run("tp", tiny_model_cfg, opt_cfg)
    np.testing.assert_allclose(r_dp.losses, r_tp.losses, rtol=2e-4, atol=2e-4)


def test_dp_equals_2d_losses(tiny_model_cfg, opt_cfg):
    r_dp = run("dp", tiny_model_cfg, opt_cfg)
    r_2d = run("dp", tiny_model_cfg, opt_cfg, mesh=MeshConfig(model=2))  # dp=4 × tp=2
    np.testing.assert_allclose(r_dp.losses, r_2d.losses, rtol=2e-4, atol=2e-4)


def test_loss_decreases(tiny_model_cfg, opt_cfg):
    r = run("dp", tiny_model_cfg, opt_cfg, steps=30)
    first = np.mean(r.losses[:5])
    last = np.mean(r.losses[-5:])
    assert last < first - 0.3, f"loss did not decrease: {first} -> {last}"


def test_pp_equals_dp(tiny_model_cfg, opt_cfg):
    """PP fill-drain schedule computes the same step as GSPMD."""
    r_dp = run("dp", tiny_model_cfg, opt_cfg)
    r_pp = run("pp", tiny_model_cfg, opt_cfg, pp_microbatches=2, mesh=MeshConfig(pipe=4, data=2, model=1))
    np.testing.assert_allclose(r_dp.losses, r_pp.losses, rtol=5e-4, atol=5e-4)


def test_3d_equals_dp(tiny_model_cfg, opt_cfg):
    """Combined DP×TP×PP on a (2,2,2) mesh matches plain DP."""
    r_dp = run("dp", tiny_model_cfg, opt_cfg)
    r_3d = run(
        "3d", tiny_model_cfg, opt_cfg,
        pp_microbatches=2, mesh=MeshConfig(pipe=2, data=2, model=2),
    )
    np.testing.assert_allclose(r_dp.losses, r_3d.losses, rtol=5e-4, atol=5e-4)


def test_pp_params_update_consistently(tiny_model_cfg, opt_cfg):
    """After PP steps, the unstacked params match the DP-trained params."""
    from dtc_tpu.parallel.pipeline import pp_unstack_params

    r_dp = run("dp", tiny_model_cfg, opt_cfg, steps=2)
    r_pp = run("pp", tiny_model_cfg, opt_cfg, steps=2, pp_microbatches=2,
               mesh=MeshConfig(pipe=2, data=4, model=1))
    p_dp = jax.device_get(r_dp.state.params)
    p_pp = jax.device_get(pp_unstack_params(r_pp.state.params))
    flat_dp = jax.tree.leaves(p_dp)
    flat_pp = jax.tree.leaves(p_pp)
    for a, b in zip(flat_dp, flat_pp):
        # Tolerance floor: Adam normalizes near-zero grads (LN biases at
        # init), so f32 reduction-order noise between the DP and PP
        # reduction shapes can flip an update's sign — bounding the
        # per-element divergence at ~lr * bias-correction ≈ 1e-4 after 2
        # steps. Real layout bugs show up at 1e-2+.
        np.testing.assert_allclose(a, b, atol=3e-4)
