"""KV-cache decode correctness: cached generation must reproduce the
no-cache oracle (full re-forward per token) exactly in fp32."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtc_tpu.generate import generate, init_cache
from dtc_tpu.models.gpt import GPT


@pytest.fixture
def model_and_params(tiny_model_cfg):
    model = GPT(tiny_model_cfg)
    x = jnp.ones((2, 4), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(7)}, x, train=False)["params"]
    return model, params


def _oracle_greedy(model, params, prompt, n):
    """No-cache oracle: full forward over the whole sequence per token."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, toks, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_forward_oracle(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (2, 5), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    got = generate(model, params, prompt, 8)
    ref = _oracle_greedy(model, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_prefill_logits_match_full_forward(model_and_params, tiny_model_cfg):
    """The decode path's prefill logits equal the training forward's —
    the cache write + offset mask reproduces plain causal attention."""
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 6), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    full = model.apply({"params": params}, prompt, train=False)
    cache = init_cache(model, 2)
    cached, _ = model.apply(
        {"params": params, "cache": cache}, prompt,
        train=False, decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full), atol=1e-5)


def test_stepwise_decode_matches_prefill(model_and_params, tiny_model_cfg):
    """Feeding the prompt one token at a time through the cache produces
    the same final-position logits as one prefill call."""
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (1, 5), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    cache = init_cache(model, 1)
    pre, _ = model.apply(
        {"params": params, "cache": cache}, prompt,
        train=False, decode=True, mutable=["cache"],
    )
    cache = init_cache(model, 1)
    for i in range(prompt.shape[1]):
        step, mut = model.apply(
            {"params": params, "cache": cache}, prompt[:, i : i + 1],
            train=False, decode=True, mutable=["cache"],
        )
        cache = mut["cache"]
    np.testing.assert_allclose(np.asarray(step[:, -1]), np.asarray(pre[:, -1]), atol=1e-5)


def test_temperature_sampling_deterministic_and_in_vocab(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.zeros((2, 3), jnp.int32)
    key = jax.random.PRNGKey(3)
    a = generate(model, params, prompt, 6, key, temperature=1.0)
    b = generate(model, params, prompt, 6, key, temperature=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 6)
    # Padded-vocab columns are masked to -1e9: sampling stays in vocab.
    assert int(a.max()) < tiny_model_cfg.vocab_size


def test_overflow_raises(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.zeros((1, tiny_model_cfg.max_seq_len - 2), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, 8)


def test_tp_sharded_decode_matches_single_device(model_and_params, tiny_model_cfg):
    """Greedy decode under a TP mesh (params + KV cache sharded over heads)
    must be token-for-token identical to single-device decode — round-3
    VERDICT next #9."""
    from flax import linen as nn
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dtc_tpu.config.schema import MeshConfig
    from dtc_tpu.parallel.mesh import mesh_from_config
    from dtc_tpu.parallel.sharding import DEFAULT_RULES, param_specs

    model, params = model_and_params
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                tiny_model_cfg.vocab_size, dtype=jnp.int32)
    want = generate(model, params, prompt, 8)

    mesh = mesh_from_config("3d", MeshConfig(pipe=1, data=2, model=4))
    specs = param_specs(params, DEFAULT_RULES)
    sharded = jax.device_put(
        params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    with mesh, nn.logical_axis_rules(DEFAULT_RULES):
        got = generate(model, sharded, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_top_k_restricts_support(model_and_params, tiny_model_cfg):
    """With top_k=1, temperature sampling must equal greedy argmax (the
    filter leaves exactly one token)."""
    model, params = model_and_params
    prompt = jnp.ones((2, 4), jnp.int32)
    greedy = generate(model, params, prompt, 6)
    k1 = generate(model, params, prompt, 6, jax.random.PRNGKey(0),
                  temperature=1.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))


def test_top_p_tiny_equals_greedy_and_filters_compose(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.ones((2, 4), jnp.int32)
    greedy = generate(model, params, prompt, 6)
    # A vanishing nucleus keeps only the argmax token.
    p_tiny = generate(model, params, prompt, 6, jax.random.PRNGKey(1),
                      temperature=1.0, top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(p_tiny), np.asarray(greedy))
    # Composed filters still sample valid vocab ids deterministically per key.
    a = generate(model, params, prompt, 6, jax.random.PRNGKey(2),
                 temperature=0.9, top_k=10, top_p=0.9)
    b = generate(model, params, prompt, 6, jax.random.PRNGKey(2),
                 temperature=0.9, top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jnp.max(a)) < tiny_model_cfg.vocab_size


def test_sampling_validation():
    import pytest as _pytest

    from dtc_tpu.config.schema import ModelConfig

    cfg = ModelConfig(vocab_size=97, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq_len=32)
    model = GPT(cfg)
    x = jnp.ones((1, 4), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)["params"]
    with _pytest.raises(ValueError, match="top_k"):
        generate(model, params, x, 2, jax.random.PRNGKey(0),
                 temperature=1.0, top_k=0)
    with _pytest.raises(ValueError, match="top_p"):
        generate(model, params, x, 2, jax.random.PRNGKey(0),
                 temperature=1.0, top_p=1.5)
