"""KV-cache decode correctness: cached generation must reproduce the
no-cache oracle (full re-forward per token) exactly in fp32, and the
fused Pallas decode backend (``decode_attention: fused``,
ops/decode_attention.py) must be token-exact against the XLA oracle
backend on every path — greedy, sampled, and TP-sharded."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtc_tpu.generate import generate, init_cache
from dtc_tpu.models.gpt import GPT


@pytest.fixture
def model_and_params(tiny_model_cfg):
    model = GPT(tiny_model_cfg)
    x = jnp.ones((2, 4), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(7)}, x, train=False)["params"]
    return model, params


def _oracle_greedy(model, params, prompt, n):
    """No-cache oracle: full forward over the whole sequence per token."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, toks, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_forward_oracle(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (2, 5), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    got = generate(model, params, prompt, 8)
    ref = _oracle_greedy(model, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_prefill_logits_match_full_forward(model_and_params, tiny_model_cfg):
    """The decode path's prefill logits equal the training forward's —
    the cache write + offset mask reproduces plain causal attention."""
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 6), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    full = model.apply({"params": params}, prompt, train=False)
    cache = init_cache(model, 2)
    cached, _ = model.apply(
        {"params": params, "cache": cache}, prompt,
        train=False, decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full), atol=1e-5)


def test_stepwise_decode_matches_prefill(model_and_params, tiny_model_cfg):
    """Feeding the prompt one token at a time through the cache produces
    the same final-position logits as one prefill call."""
    model, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (1, 5), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    cache = init_cache(model, 1)
    pre, _ = model.apply(
        {"params": params, "cache": cache}, prompt,
        train=False, decode=True, mutable=["cache"],
    )
    cache = init_cache(model, 1)
    for i in range(prompt.shape[1]):
        step, mut = model.apply(
            {"params": params, "cache": cache}, prompt[:, i : i + 1],
            train=False, decode=True, mutable=["cache"],
        )
        cache = mut["cache"]
    np.testing.assert_allclose(np.asarray(step[:, -1]), np.asarray(pre[:, -1]), atol=1e-5)


def test_temperature_sampling_deterministic_and_in_vocab(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.zeros((2, 3), jnp.int32)
    key = jax.random.PRNGKey(3)
    a = generate(model, params, prompt, 6, key, temperature=1.0)
    b = generate(model, params, prompt, 6, key, temperature=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 6)
    # Padded-vocab columns are masked to -1e9: sampling stays in vocab.
    assert int(a.max()) < tiny_model_cfg.vocab_size


def test_overflow_raises(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.zeros((1, tiny_model_cfg.max_seq_len - 2), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, 8)


def test_fused_and_xla_decode_token_exact(model_and_params, tiny_model_cfg):
    """The decode_attention knob is a pure execution-strategy switch:
    fused and xla must produce IDENTICAL tokens (greedy and sampled,
    same rng) — argmax/categorical decisions don't tolerate drift, so
    this is the token-level parity bar the ISSUE sets."""
    _, params = model_and_params
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (2, 5), 0, tiny_model_cfg.vocab_size, jnp.int32
    )
    outs = {}
    for backend in ("fused", "xla"):
        model = GPT(dataclasses.replace(tiny_model_cfg, decode_attention=backend))
        greedy = generate(model, params, prompt, 8)
        sampled = generate(model, params, prompt, 8, jax.random.PRNGKey(9),
                           temperature=0.8, top_k=12, top_p=0.9)
        outs[backend] = (np.asarray(greedy), np.asarray(sampled))
    np.testing.assert_array_equal(outs["fused"][0], outs["xla"][0])
    np.testing.assert_array_equal(outs["fused"][1], outs["xla"][1])


def test_cache_layout_roundtrip(model_and_params, tiny_model_cfg):
    """The packed (B, S, H·D) cache is written by lane-aligned
    dynamic_update_slice: feeding the prompt token-by-token must build
    byte-identical cache contents to one prefill write, slots beyond the
    write frontier must stay zero, and the packed buffer must reshape
    (bitcast) to the (B, S, H, D) head layout the XLA oracle consumes."""
    model, params = model_and_params
    cfg = tiny_model_cfg
    prompt = jax.random.randint(
        jax.random.PRNGKey(4), (1, 6), 0, cfg.vocab_size, jnp.int32
    )
    cache = init_cache(model, 1)
    _, pre = model.apply(
        {"params": params, "cache": cache}, prompt,
        train=False, decode=True, mutable=["cache"],
    )
    cache = init_cache(model, 1)
    for i in range(prompt.shape[1]):
        _, mut = model.apply(
            {"params": params, "cache": cache}, prompt[:, i : i + 1],
            train=False, decode=True, mutable=["cache"],
        )
        cache = mut["cache"]
    # atol 1e-5: the 6-token prefill matmul and the 1-token step matmul
    # vectorize differently on CPU (same tolerance the prefill-vs-full
    # logits tests above use).
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        pre["cache"], cache,
    )
    k0 = np.asarray(
        pre["cache"]["stage"]["blocks"]["Block_0"]["attn"]["k"][0]  # layer 0
    )
    assert k0.shape == (1, cfg.max_seq_len, cfg.n_heads * cfg.head_dim)
    assert np.any(k0[:, : prompt.shape[1]] != 0)
    assert np.all(k0[:, prompt.shape[1]:] == 0), "write leaked past the frontier"
    # Layout check with teeth: the packed buffer's two consumers — the
    # fused kernel (per-head LANE slices) and the XLA oracle (a reshape
    # to (B, S, H, D)) — must agree on this model-produced cache. Were
    # heads packed any way other than D-contiguous, the lane slices and
    # the reshape would read different columns and disagree.
    from dtc_tpu.ops.attention import decode_attention as xla_oracle
    from dtc_tpu.ops.decode_attention import fused_decode_attention

    h, d, s = cfg.n_heads, cfg.head_dim, cfg.max_seq_len
    v0 = np.asarray(
        pre["cache"]["stage"]["blocks"]["Block_0"]["attn"]["v"][0]
    )
    q = jax.random.normal(jax.random.PRNGKey(7), (1, 1, h * d), k0.dtype)
    start = jnp.int32(prompt.shape[1] - 1)
    from_lanes = fused_decode_attention(
        q, jnp.asarray(k0), jnp.asarray(v0), start, h=h, d=d
    )
    from_reshape = xla_oracle(
        q.reshape(1, 1, h, d),
        jnp.asarray(k0).reshape(1, s, h, d),
        jnp.asarray(v0).reshape(1, s, h, d),
        start,
    )
    np.testing.assert_allclose(
        np.asarray(from_lanes).reshape(1, 1, h, d),
        np.asarray(from_reshape), atol=1e-5,
    )


def test_fused_decode_kernel_matches_fp32_oracle(monkeypatch):
    """Interpret-mode kernel check vs the fp32 XLA oracle, both grid
    flavors: single-tile (cache fits one KV block) and blocked
    (online-softmax walk with beyond-frontier block skip). The blocked
    thresholds are shrunk so that path runs at a CPU-interpretable shape
    (the same monkeypatch idiom test_flash_attention.py uses for
    _PACKED_MAX_T)."""
    from dtc_tpu.ops import decode_attention as fused_mod
    from dtc_tpu.ops.attention import decode_attention

    monkeypatch.setattr(fused_mod, "_DECODE_MAX_SINGLE_S", 128)
    monkeypatch.setattr(fused_mod, "_DECODE_BLOCK_S", 64)
    for (b, s, h, d, start) in [
        (2, 64, 4, 16, 13),          # single-tile, ungrouped heads (g=h)
        (1, 128, 4, 32, 127),        # single-tile, lane-grouped (g=4)
        (1, 256, 2, 8, 100),         # blocked path (s > single-tile max)
    ]:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(s), 3)
        q = jax.random.normal(kq, (b, 1, h, d), jnp.float32)
        k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
        v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
        valid = (jnp.arange(s) <= start)[None, :, None, None]
        k = jnp.where(valid, k, 0.0)
        v = jnp.where(valid, v, 0.0)
        ref = decode_attention(q, k, v, jnp.int32(start))
        got = fused_mod.fused_decode_attention(
            q.reshape(b, 1, h * d), k.reshape(b, s, h * d),
            v.reshape(b, s, h * d), jnp.int32(start), h=h, d=d,
        ).reshape(b, 1, h, d)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5,
            err_msg=f"shape b={b} s={s} h={h} d={d} start={start}",
        )
    # Unsupported cache lengths must be rejected loudly (the model layer
    # gates on supports() and falls back to the xla path).
    assert not fused_mod.supports(256 + 17)
    with pytest.raises(ValueError, match="cache length"):
        fused_mod.fused_decode_attention(
            jnp.zeros((1, 1, 8)), jnp.zeros((1, 273, 8)),
            jnp.zeros((1, 273, 8)), jnp.int32(0), h=1, d=8,
        )


@pytest.mark.parametrize("backend", ["fused", "xla"])
def test_tp_sharded_decode_matches_single_device(model_and_params, tiny_model_cfg,
                                                 backend):
    """Greedy decode under a TP mesh (params + KV cache sharded over heads
    — the packed cache's lane axis carries the "heads" logical name) must
    be token-for-token identical to single-device decode — round-3
    VERDICT next #9, now for BOTH decode backends."""
    from flax import linen as nn
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dtc_tpu.config.schema import MeshConfig
    from dtc_tpu.parallel.mesh import mesh_from_config
    from dtc_tpu.parallel.sharding import DEFAULT_RULES, param_specs

    _, params = model_and_params
    model = GPT(dataclasses.replace(tiny_model_cfg, decode_attention=backend))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                tiny_model_cfg.vocab_size, dtype=jnp.int32)
    want = generate(model, params, prompt, 8)

    mesh = mesh_from_config("3d", MeshConfig(pipe=1, data=2, model=4))
    specs = param_specs(params, DEFAULT_RULES)
    sharded = jax.device_put(
        params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    with mesh, nn.logical_axis_rules(DEFAULT_RULES):
        got = generate(model, sharded, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_top_k_restricts_support(model_and_params, tiny_model_cfg):
    """With top_k=1, temperature sampling must equal greedy argmax (the
    filter leaves exactly one token)."""
    model, params = model_and_params
    prompt = jnp.ones((2, 4), jnp.int32)
    greedy = generate(model, params, prompt, 6)
    k1 = generate(model, params, prompt, 6, jax.random.PRNGKey(0),
                  temperature=1.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))


def test_top_p_tiny_equals_greedy_and_filters_compose(model_and_params, tiny_model_cfg):
    model, params = model_and_params
    prompt = jnp.ones((2, 4), jnp.int32)
    greedy = generate(model, params, prompt, 6)
    # A vanishing nucleus keeps only the argmax token.
    p_tiny = generate(model, params, prompt, 6, jax.random.PRNGKey(1),
                      temperature=1.0, top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(p_tiny), np.asarray(greedy))
    # Composed filters still sample valid vocab ids deterministically per key.
    a = generate(model, params, prompt, 6, jax.random.PRNGKey(2),
                 temperature=0.9, top_k=10, top_p=0.9)
    b = generate(model, params, prompt, 6, jax.random.PRNGKey(2),
                 temperature=0.9, top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(jnp.max(a)) < tiny_model_cfg.vocab_size


def test_sampling_validation():
    import pytest as _pytest

    from dtc_tpu.config.schema import ModelConfig

    cfg = ModelConfig(vocab_size=97, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq_len=32)
    model = GPT(cfg)
    x = jnp.ones((1, 4), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)["params"]
    with _pytest.raises(ValueError, match="top_k"):
        generate(model, params, x, 2, jax.random.PRNGKey(0),
                 temperature=1.0, top_k=0)
    with _pytest.raises(ValueError, match="top_p"):
        generate(model, params, x, 2, jax.random.PRNGKey(0),
                 temperature=1.0, top_p=1.5)
