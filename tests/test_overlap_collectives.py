"""Overlapped training collectives (ISSUE 12, ops/overlap_collectives.py).

Three layers of evidence, all on the 8-virtual-device CPU mesh:

- **op parity** — the fused all-gather-matmul and the streamed grad
  reduce-scatter match the single-dot XLA oracle to fp roundoff, forward
  and backward, for BOTH transports: ``decomposed`` (ppermute rings) and
  ``pallas`` (the REAL RDMA kernels, run under Pallas interpret mode —
  the same kernels a TPU executes). Ring edge cases: degenerate 1-shard
  mesh, non-divisible block tails, batch narrower than the ring, bf16
  inputs.
- **training parity** — a full ``parallel: fsdp`` /
  ``collectives: overlapped`` run is loss-parity with the xla path, and
  the DP×FSDP×TP mesh (configs/train_config_3d.yaml's shape) is
  loss-parity with plain DP.
- **HLO structure** — the overlapped train step's compiled module holds
  the ring transport (collective-permute on this CPU) and has LOST the
  serialized per-layer kernel all-gathers; a TPU lowering of the op
  (``jax.export`` — no TPU needed) holds the Pallas custom-calls and no
  all-gather at all.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from dtc_tpu.ops import overlap_collectives as oc
from tests.conftest import make_train_cfg

pytestmark = pytest.mark.kernels


@pytest.fixture
def mesh8():
    return jax.make_mesh((8,), ("data",))


def _rand(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


# ---------------------------------------------------------------------------
# op-level parity vs the XLA oracle


@pytest.mark.parametrize("backend", ["decomposed", "pallas"])
@pytest.mark.parametrize("shard_axis", [0, 1])
def test_ag_matmul_parity_fwd_bwd(mesh8, backend, shard_axis, monkeypatch):
    """Both transports, both shard modes: fwd product and BOTH grads
    match the single-dot oracle to fp roundoff. The pallas rows drive the
    real RDMA kernels in interpret mode (DTC_OVERLAP=pallas is the
    documented hook)."""
    monkeypatch.setenv("DTC_OVERLAP", backend)
    rng = np.random.default_rng(0)
    x = _rand(rng, 8, 4, 64)
    w = _rand(rng, 64, 128)

    def f(a, b):
        return jnp.sum(jnp.sin(oc.overlap_dense_matmul(
            a, b, shard_axis=shard_axis, axis_name="data", backend=backend
        )))

    with mesh8:
        y = jax.jit(lambda a, b: oc.overlap_dense_matmul(
            a, b, shard_axis=shard_axis, axis_name="data", backend=backend
        ))(x, w)
        dx, dw = jax.jit(jax.grad(f, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(y, x @ w, rtol=1e-5, atol=1e-5)
    ref_dx, ref_dw = jax.grad(
        lambda a, b: jnp.sum(jnp.sin(a @ b)), argnums=(0, 1)
    )(x, w)
    np.testing.assert_allclose(dx, ref_dx, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(dw, ref_dw, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("backend", ["decomposed", "pallas"])
def test_ag_matmul_bf16_parity(mesh8, backend):
    """bf16 inputs: ring partials accumulate in fp32 (the module
    contract), so the ring matches the oracle within bf16 resolution."""
    rng = np.random.default_rng(1)
    x = _rand(rng, 8, 4, 64, dtype=jnp.bfloat16)
    w = _rand(rng, 64, 128, dtype=jnp.bfloat16)
    with mesh8:
        y = jax.jit(lambda a, b: oc.overlap_dense_matmul(
            a, b, shard_axis=0, axis_name="data", backend=backend
        ))(x, w)
    assert y.dtype == jnp.bfloat16
    ref = (
        x.astype(jnp.float32) @ w.astype(jnp.float32)
    ).astype(jnp.bfloat16).astype(np.float32)
    np.testing.assert_allclose(
        y.astype(np.float32), ref, rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("backend", ["decomposed", "pallas"])
@pytest.mark.parametrize("shard_axis", [0, 1])
def test_reduce_scatter_matmul_vs_psum_scatter(
    mesh8, backend, shard_axis,
):
    """The standalone streamed reduce-scatter against the textbook
    oracle: psum_scatter of the local partial products."""
    rng = np.random.default_rng(2)
    a = _rand(rng, 16, 64)
    b = _rand(rng, 16, 128)
    with mesh8:
        got = jax.jit(lambda p, q: oc.reduce_scatter_matmul(
            p, q, shard_axis=shard_axis, axis_name="data", mesh=mesh8,
            backend=backend,
        ))(a, b)

        from dtc_tpu.utils.compat import shard_map

        def oracle_local(al, bl):
            part = jnp.einsum(
                "mk,mn->kn", al, bl, preferred_element_type=jnp.float32
            )
            return lax.psum_scatter(
                part, "data", scatter_dimension=shard_axis, tiled=True
            )

        oracle = jax.jit(shard_map(
            oracle_local, mesh=mesh8, in_specs=(P("data"), P("data")),
            out_specs=P("data", None) if shard_axis == 0 else P(None, "data"),
            axis_names={"data"}, check_vma=False,
        ))(a, b)
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-5)


def test_degenerate_single_shard_mesh():
    """Ring of 1: the op must collapse to the plain dot (no shard_map, no
    permutes) and stay grad-correct."""
    mesh1 = jax.make_mesh((1,), ("data",))
    rng = np.random.default_rng(3)
    x = _rand(rng, 2, 4, 64)
    w = _rand(rng, 64, 32)
    with mesh1:
        y = jax.jit(lambda a, b: oc.overlap_dense_matmul(
            a, b, shard_axis=0, axis_name="data"
        ))(x, w)
    np.testing.assert_allclose(y, x @ w, rtol=1e-6, atol=1e-6)


def test_non_divisible_tails_fall_back(mesh8):
    """Shard or batch dims the ring cannot split evenly take the
    serialized-dot fallback — parity held, no crash (the 'auto-fallback
    for shapes the kernels don't support' contract)."""
    rng = np.random.default_rng(4)
    cases = [
        ((8, 4, 60), (60, 128), 0),   # K=60 not divisible by ring 8
        ((8, 4, 64), (64, 100), 1),   # N=100 not divisible by ring 8
        ((3, 4, 64), (64, 128), 0),   # batch 3 narrower than the ring
    ]
    for xshape, wshape, sa in cases:
        x = _rand(rng, *xshape)
        w = _rand(rng, *wshape)
        with mesh8:
            y = jax.jit(lambda a, b, sa=sa: oc.overlap_dense_matmul(
                a, b, shard_axis=sa, axis_name="data"
            ))(x, w)
        np.testing.assert_allclose(y, x @ w, rtol=1e-5, atol=1e-5)


def test_eager_and_axisless_calls_are_plain_dots():
    """model.init runs eagerly and generate() runs without FSDP rules —
    both must silently take the plain-dot path."""
    rng = np.random.default_rng(5)
    x = _rand(rng, 2, 4, 16)
    w = _rand(rng, 16, 8)
    y = oc.overlap_dense_matmul(x, w, shard_axis=0, axis_name="data")
    np.testing.assert_allclose(y, x @ w, rtol=1e-6)
    y2 = jax.jit(lambda a, b: oc.overlap_dense_matmul(
        a, b, shard_axis=0, axis_name=None
    ))(x, w)
    np.testing.assert_allclose(y2, x @ w, rtol=1e-6)


def test_fsdp_axis_in_scope_resolution(mesh8):
    """The sharding.py thread: the FSDP axis is visible exactly when the
    active rules shard embed_p onto a live mesh axis — and sequence-
    parallel rule sets defer (overlap+SP composition is future work)."""
    from flax import linen as nn

    from dtc_tpu.parallel.sharding import (
        DEFAULT_RULES, FSDP_RULES, fsdp_axis_in_scope, ring_rules_from,
    )

    with mesh8, nn.logical_axis_rules(FSDP_RULES):
        assert fsdp_axis_in_scope() == "data"
    with mesh8, nn.logical_axis_rules(DEFAULT_RULES):
        assert fsdp_axis_in_scope() is None
    # ring-derived FSDP rules map seq -> model; on a mesh where model is
    # trivial the ring is inert and FSDP overlap still applies…
    with mesh8, nn.logical_axis_rules(ring_rules_from(FSDP_RULES)):
        assert fsdp_axis_in_scope() == "data"
    # …but with a live model axis, SP owns the activations: defer.
    mesh42 = jax.make_mesh((4, 2), ("data", "model"))
    with mesh42, nn.logical_axis_rules(ring_rules_from(FSDP_RULES)):
        assert fsdp_axis_in_scope() is None
    with mesh42, nn.logical_axis_rules(FSDP_RULES):
        assert fsdp_axis_in_scope() == "data"


# ---------------------------------------------------------------------------
# training parity (the trainer-level route: TrainConfig.collectives)


@pytest.mark.quick
def test_fsdp_overlapped_matches_xla_losses(tiny_model_cfg, opt_cfg):
    """The acceptance bar: the overlapped FSDP step is grad-parity with
    the XLA path to fp roundoff — 4 full train steps, loss-for-loss."""
    from dtc_tpu.train.trainer import train

    r_xla = train(make_train_cfg("fsdp"), tiny_model_cfg, opt_cfg)
    r_ovl = train(
        make_train_cfg("fsdp", collectives="overlapped"),
        tiny_model_cfg, opt_cfg,
    )
    np.testing.assert_allclose(
        r_ovl.losses, r_xla.losses, rtol=2e-4, atol=2e-4
    )
    # Param sharding unchanged: the ring consumes the SAME placement.
    qk = r_ovl.state.params["stage"]["blocks"]["Block_0"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P(None, "data")


@pytest.mark.quick
def test_3d_overlapped_matches_dp_losses(tiny_model_cfg, opt_cfg):
    """The train_config_3d.yaml mode: DP×FSDP×TP (data=4, model=2) with
    overlapped collectives is loss-parity with plain DP — the ring rides
    the data axis while the explicit Megatron psums carry TP."""
    from dtc_tpu.config.schema import MeshConfig
    from dtc_tpu.train.trainer import train

    r_dp = train(make_train_cfg("dp"), tiny_model_cfg, opt_cfg)
    r_3d = train(
        make_train_cfg(
            "fsdp", collectives="overlapped",
            mesh=MeshConfig(data=4, model=2),
        ),
        tiny_model_cfg, opt_cfg,
    )
    np.testing.assert_allclose(r_3d.losses, r_dp.losses, rtol=5e-4, atol=5e-4)
    qk = r_3d.state.params["stage"]["blocks"]["Block_0"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P(None, "data", "model")


@pytest.mark.quick
def test_dropout_parity_under_partitionable_threefry(tiny_model_cfg, opt_cfg):
    """With dropout ACTIVE the two modes stay loss-parity under the
    partitionable threefry (the modern default; sharding-invariant random
    bits). Under this jax's LEGACY threefry, random bits are
    sharding-layout-dependent, so the ring's layouts select different —
    equally valid — dropout masks (the established 1F1B-vs-GPipe dropout
    semantics; create_1f1b_train_step documents the same class). This
    test pins that the divergence is mask SELECTION, not math: flip the
    flag and the trajectories coincide."""
    import dataclasses

    from dtc_tpu.train.trainer import train

    prev = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        drop = dataclasses.replace(tiny_model_cfg, dropout=0.1)
        r_xla = train(make_train_cfg("fsdp", steps=3), drop, opt_cfg)
        r_ovl = train(
            make_train_cfg("fsdp", steps=3, collectives="overlapped"),
            drop, opt_cfg,
        )
        np.testing.assert_allclose(
            r_ovl.losses, r_xla.losses, rtol=5e-4, atol=5e-4
        )
    finally:
        jax.config.update("jax_threefry_partitionable", prev)


def test_overlapped_rejected_under_pipeline(tiny_model_cfg, opt_cfg):
    from dtc_tpu.train.trainer import train

    with pytest.raises(ValueError, match="pipeline"):
        train(
            make_train_cfg("pp", collectives="overlapped", pp_microbatches=2),
            tiny_model_cfg, opt_cfg,
        )


def test_resolve_collectives_routes_both_configs(tiny_model_cfg):
    """The knob may arrive via EITHER config: a model-level 'overlapped'
    must survive the train-level 'xla' default (not be silently
    reverted), and the pipeline rejection must fire on every route in —
    including when both configs already agree on 'overlapped'."""
    import dataclasses

    from dtc_tpu.train.train_step import resolve_collectives

    t_xla = make_train_cfg("fsdp")
    model_ovl = dataclasses.replace(tiny_model_cfg, collectives="overlapped")
    assert resolve_collectives(t_xla, model_ovl).collectives == "overlapped"
    assert resolve_collectives(
        dataclasses.replace(t_xla, collectives="overlapped"), tiny_model_cfg
    ).collectives == "overlapped"
    # xla + xla: untouched (and no gratuitous replace).
    assert resolve_collectives(t_xla, tiny_model_cfg) is tiny_model_cfg
    t_pp = make_train_cfg(
        "pp", collectives="overlapped", pp_microbatches=2
    )
    with pytest.raises(ValueError, match="pipeline"):
        resolve_collectives(t_pp, model_ovl)


# ---------------------------------------------------------------------------
# HLO structure: the ring replaces the serialized gathers


@pytest.mark.slow
def test_overlapped_step_hlo_structure():
    """The compiled overlapped FSDP step (CPU lowering): the ring
    transport is present and the serialized layer-scan all-gathers are
    gone — the only "/blocks/"-scope gathers left are the rank-1 bias/LN
    assemblies (XLA-managed by design). The xla-mode module of the SAME
    config shows the serialized rank>=2 block gathers, proving the
    assertion bites."""
    from dtc_tpu.analysis import hlo
    from dtc_tpu.analysis.lowering import (
        audit_model_cfg, audit_opt_cfg, compiled_train_hlo,
    )
    from dtc_tpu.config.schema import MeshConfig
    from dtc_tpu.parallel.sharding import FSDP_RULES

    def block_gathers(txt):
        return [
            (d, dims) for d, dims, scope in hlo.all_gather_entries(txt)
            if "/blocks/" in scope and len(dims) >= 2
        ]

    ovl = compiled_train_hlo(
        "fsdp", MeshConfig(), audit_model_cfg(collectives="overlapped"),
        audit_opt_cfg(), FSDP_RULES,
    )
    census = hlo.collective_census(ovl)
    assert census.get("collective-permute", {}).get("count", 0) > 0, census
    assert block_gathers(ovl) == [], block_gathers(ovl)

    xla = compiled_train_hlo(
        "fsdp", MeshConfig(), audit_model_cfg(), audit_opt_cfg(), FSDP_RULES,
    )
    assert block_gathers(xla), (
        "the serialized baseline no longer shows layer-scan gathers — "
        "the structural assertion above is vacuous"
    )


def test_tpu_lowering_contains_pallas_custom_calls(mesh8, monkeypatch):
    """``jax.export`` for platform "tpu" (no TPU needed): the fused op's
    forward AND backward lower to Pallas custom-calls — and contain NO
    all-gather instruction at all (the gather IS the kernels' RDMA)."""
    from jax import export

    # Export must lower the REAL kernels, not interpret-mode emulation.
    monkeypatch.setattr(oc, "_interpret", lambda: False)
    rng = np.random.default_rng(6)
    x = _rand(rng, 8, 4, 1024)
    w = _rand(rng, 1024, 1024)  # ring blocks of 128: hardware-aligned

    def f(a, b):
        # sin keeps the primal output live in the grad program — without
        # it the forward kernel would be dead code under jax.grad (the
        # cotangent of a plain sum is independent of the primal).
        return jnp.sum(jnp.sin(oc.overlap_dense_matmul(
            a, b, shard_axis=0, axis_name="data", mesh=mesh8,
            backend="pallas",
        )))

    with mesh8:
        exp = export.export(
            jax.jit(jax.grad(f, argnums=(0, 1))), platforms=("tpu",)
        )(x, w)
    txt = exp.mlir_module()
    assert txt.count("tpu_custom_call") >= 3, (
        "expected the ag fwd + ag re-gather (dx) + streamed-rs (dw) "
        "kernels as tpu_custom_calls"
    )
    assert "all_gather" not in txt and "all-gather" not in txt
    # The lowering stamps kernel_name onto the custom-call lines — the
    # exact fingerprint the census rules key the ring transport on
    # (name-matched, so foreign Pallas kernels can never satisfy it).
    from dtc_tpu.analysis.hlo import (
        OVERLAP_KERNEL_TOKENS, PALLAS_CUSTOM_CALL_TARGET,
    )

    assert PALLAS_CUSTOM_CALL_TARGET in txt
    assert all(tok in txt for tok in OVERLAP_KERNEL_TOKENS)


# ---------------------------------------------------------------------------
# audit integration: the new entries' rule wiring (fabricated census)


def test_census_rules_for_overlapped_entries():
    """The graph-audit satellite, unit-level: an overlapped entry with
    neither permutes nor Pallas custom-calls trips the required-
    collective rule; either fingerprint alone satisfies it; a surviving
    per-layer kernel gather trips the serialized-layer-gather rule."""
    from dtc_tpu.analysis.lowering import Artifact
    from dtc_tpu.analysis.rules import audit_census

    def art(hlo_text):
        return Artifact(
            name="train_fsdp_overlapped", kind="train", parallel="fsdp",
            mesh_shape={"data": 8}, batch=8, seq_len=32,
            hlo_text=hlo_text, stablehlo_text="", expected_donated=0,
            param_shapes=[("f32", (4, 64, 128))], weak_outputs=0,
            n_layers=4, moe_experts=0, compute_dtype="float32",
        )

    bare = art("ENTRY %main {\n  %r = f32[8] add(x, y)\n}")
    rules_hit = [f.rule for f in audit_census(bare)]
    assert "census.required_collective" in rules_hit

    permute = art(
        "ENTRY %main {\n"
        "  %p = f32[8,128] collective-permute(%a)\n}"
    )
    assert "census.required_collective" not in [
        f.rule for f in audit_census(permute)
    ]

    # The overlap KERNELS' custom-calls satisfy the transport check —
    # matched by kernel_name, so a foreign Pallas kernel (flash, decode)
    # does NOT (the check would otherwise be vacuous on TPU).
    pallas = art(
        "ENTRY %main {\n"
        '  %c = f32[8,128] custom-call(%a), custom_call_target='
        '"tpu_custom_call", kernel_name = "_overlap_ag_matmul_kernel"\n}'
    )
    assert "census.required_collective" not in [
        f.rule for f in audit_census(pallas)
    ]
    foreign = art(
        "ENTRY %main {\n"
        '  %c = f32[8,128] custom-call(%a), custom_call_target='
        '"tpu_custom_call", kernel_name = "_flash_fwd_kernel"\n}'
    )
    assert "census.required_collective" in [
        f.rule for f in audit_census(foreign)
    ]

    # A rank-2 gather scoped INSIDE the layer scan trips the rule…
    leaked = art(
        "ENTRY %main {\n"
        "  %p = f32[8,128] collective-permute(%a)\n"
        "  %g = f32[64,128] all-gather(%b), metadata={op_name="
        '"jit(s)/fwd/GPT/stage/while/body/blocks/Block_0/mlp/fc1/dot"}\n}'
    )
    assert "census.serialized_layer_gather" in [
        f.rule for f in audit_census(leaked)
    ]
    # …while the SAME shape at the head (lm_head on the tiny model) and
    # rank-1 bias/LN assemblies inside blocks are legitimate.
    legit = art(
        "ENTRY %main {\n"
        "  %p = f32[8,128] collective-permute(%a)\n"
        "  %g = f32[64,128] all-gather(%b), metadata={op_name="
        '"jit(s)/fwd/GPT/head/dot_general"}\n'
        "  %h = f32[64] all-gather(%c), metadata={op_name="
        '"jit(s)/fwd/GPT/stage/while/body/blocks/Block_0/ln_1/mul"}\n}'
    )
    assert "census.serialized_layer_gather" not in [
        f.rule for f in audit_census(legit)
    ]


def test_stacked_gather_rule_catches_compute_dtype_cast():
    """The hoisted-stacked-gather rule accepts the COMPUTE dtype too: XLA
    sinks the fp32->bf16 convert below the gather, so the hoisted form of
    an fp32 stacked param can land as bf16[L, ...] — while incidental
    integer buffers sharing the leading dim stay excluded."""
    from dtc_tpu.analysis.lowering import Artifact
    from dtc_tpu.analysis.rules import audit_census

    def art(body):
        return Artifact(
            name="train_fsdp", kind="train", parallel="fsdp",
            mesh_shape={"data": 8}, batch=8, seq_len=32,
            hlo_text=(
                "ENTRY %m {\n  %ar = f32[1] all-reduce(%g)\n"
                "  %pid = u32[] partition-id()\n" + body + "}"
            ),
            stablehlo_text="", expected_donated=0,
            param_shapes=[("f32", (4, 64, 128))], weak_outputs=0,
            n_layers=4, moe_experts=0, compute_dtype="bfloat16",
        )

    cast = art("  %ag = bf16[4,64,128]{2,1,0} all-gather(%w)\n")
    assert "census.stacked_param_gather" in [
        f.rule for f in audit_census(cast)
    ]
    idx = art("  %ag = s32[4,32,1]{2,1,0} all-gather(%i)\n")
    assert "census.stacked_param_gather" not in [
        f.rule for f in audit_census(idx)
    ]


def test_pallas_custom_call_census_parser():
    from dtc_tpu.analysis import hlo

    txt = (
        "ENTRY %main {\n"
        '  %c1 = f32[8,128] custom-call(%a), custom_call_target='
        '"tpu_custom_call"\n'
        '  %c2 = (f32[4,4], f32[2,2]) custom-call(%b), custom_call_target='
        '"tpu_custom_call"\n'
        '  %other = f32[8] custom-call(%d), custom_call_target="cholesky"\n}'
    )
    cc = hlo.pallas_custom_calls(txt)
    assert cc["count"] == 2
    assert cc["bytes"] == 8 * 128 * 4 + (16 + 4) * 4
    census = hlo.collective_census(txt)
    assert census["pallas_custom_call"] == cc
    # kernel-free module: no row at all (pre-ISSUE-12 baselines stay
    # byte-identical).
    assert "pallas_custom_call" not in hlo.collective_census("%r = add()")
    # The NAME-matched overlap-kernel parser: only kernel_name lines with
    # an overlap token count (foreign Pallas kernels are excluded).
    named = (
        "ENTRY %m {\n"
        '  %c1 = f32[8,128] custom-call(%a), custom_call_target='
        '"tpu_custom_call", kernel_name = "_overlap_rs_matmul_kernel"\n'
        '  %c2 = f32[8,128] custom-call(%b), custom_call_target='
        '"tpu_custom_call", kernel_name = "_flash_fwd_kernel"\n}'
    )
    ok = hlo.overlap_kernel_custom_calls(named)
    assert ok == {"count": 1, "bytes": 8 * 128 * 4}


# ---------------------------------------------------------------------------
# metrics: the 3d comm terms + devprof recognition


def test_tp_sharded_param_count_matches_rule_table(tiny_model_cfg):
    """The estimator's TP-sharded split must equal what the rule table
    actually shards over "model" — computed from param_specs, so the two
    can never silently diverge."""
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.parallel.sharding import param_specs
    from dtc_tpu.utils.metrics import tp_sharded_param_count

    model = GPT(tiny_model_cfg)
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
            jnp.ones((1, tiny_model_cfg.max_seq_len), jnp.int32),
            train=False,
        )
    )["params"]
    specs = param_specs(params)
    total = 0
    for leaf, spec in zip(
        jax.tree.leaves(params),
        jax.tree.leaves(specs, is_leaf=lambda s: isinstance(s, P)),
    ):
        if "model" in tuple(spec):
            total += int(np.prod(leaf.shape))
    assert tp_sharded_param_count(tiny_model_cfg) == total


def test_comm_bytes_3d_terms(tiny_model_cfg):
    """Hand-computed DP×FSDP×TP estimate: FSDP factor 3 over the honest
    per-device share (TP-sharded params / model + TP-replicated rest),
    plus the unchanged Megatron activation term."""
    from dtc_tpu.models.gpt import param_count
    from dtc_tpu.utils.metrics import (
        comm_bytes_per_step, tp_sharded_param_count,
    )

    cfg = tiny_model_cfg
    mesh = {"data": 4, "model": 2, "pipe": 1}
    got = comm_bytes_per_step(cfg, 8, 32, mesh, "fsdp")
    n, n_tp = param_count(cfg), tp_sharded_param_count(cfg)
    local = n_tp / 2 + (n - n_tp)
    assert got["dp_allreduce"] == pytest.approx(3.0 * 3 / 4 * local * 4)
    act = 8 * 32 * cfg.d_model * 4 / 4          # per-device batch shard
    assert got["tp_allreduce"] == pytest.approx(
        4.0 * cfg.n_layers * 2.0 * 1 / 2 * act
    )
    # Pure FSDP (model=1) keeps the historical formula bit-for-bit — the
    # committed train_fsdp baseline pins it.
    old = comm_bytes_per_step(cfg, 8, 32, {"data": 8}, "fsdp")
    assert old["dp_allreduce"] == pytest.approx(3.0 * 7 / 8 * n * 4)


def test_devprof_fused_collective_recognition():
    """Device rows named after the overlap kernels count as fused
    collectives (compute + structural overlap), and the breakdown view
    reports exposed vs hidden per collective."""
    from dtc_tpu.obs.devprof import (
        OpRow, attribute, overlap_breakdown,
    )

    def row(name, hlo_op, t0, dur, kind):
        return OpRow(
            name=name, hlo_op=hlo_op, hlo_module="m", scope="",
            t0_s=t0, dur_s=dur, pid=1, tid=1, kind=kind,
        )

    rows = [
        row("fusion.1", "fusion.1", 0.0, 1.0, "compute"),
        # a collective half-hidden under the fusion
        row("all-gather.2", "all-gather.2", 0.5, 1.0, "collective"),
        # the fused ring kernel
        row(
            "overlap_ag_matmul_kernel", "custom-call.3", 2.0, 0.5,
            "compute",
        ),
    ]
    att = attribute(rows)
    assert att.fused_collective_s == pytest.approx(0.5)
    assert att.collective_s == pytest.approx(1.0)
    assert att.overlap_ratio == pytest.approx(0.5)

    bd = overlap_breakdown(rows)
    coll = [d for d in bd if not d["fused"]]
    assert len(coll) == 1
    assert coll[0]["overlapped_s"] == pytest.approx(0.5)
    assert coll[0]["exposed_s"] == pytest.approx(0.5)
    assert coll[0]["under"][0][0] == "fusion.1"
    fused = [d for d in bd if d["fused"]]
    assert len(fused) == 1 and fused[0]["exposed_s"] == 0.0
