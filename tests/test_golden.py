"""Golden-loss regression: fixed-seed trajectories pinned in-tree.

The reference's correctness anchor is its committed loss curves
(`/root/reference/outputs/dp/log.csv`: 9.387 -> 5.584 over 5000 steps).
Round-2 VERDICT "Missing" #2: all parity here was strategy-vs-strategy, so
a numerics regression shifting every strategy identically passed CI. These
tests pin (a) absolute per-step losses for each strategy against committed
goldens, and (b) the flagship init-loss invariant loss(step 0) ~= log(vocab)
— the same invariant behind the reference's 9.387 first-step anchor
(log(50258) = 10.825 before the first update; 9.387 is one update later).

Regenerate (ONLY after an intentional numerics change):
    python tests/test_golden.py regen
"""

import json
import os
import sys

import numpy as np

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens.json")

# Strategy -> train-config overrides (+ optional "model" overrides).
# Mirrors the parity matrix; "moe" pins the Switch routing/aux numerics
# absolutely — per-strategy parity alone would miss a routing regression
# that shifts every run identically.
GOLDEN_RUNS = {
    "dp": dict(),
    "tp": dict(mesh=dict(model=4, data=2)),
    "pp": dict(pp_microbatches=2, mesh=dict(pipe=4, data=2)),
    "3d": dict(pp_microbatches=2, mesh=dict(pipe=2, data=2, model=2)),
    "moe": dict(
        mesh=dict(model=4, data=2),
        model=dict(moe_experts=4, moe_top_k=2),
    ),
}
GOLDEN_STEPS = 8


def _run(strategy: str, overrides: dict):
    from dtc_tpu.config.schema import MeshConfig
    from dtc_tpu.train.trainer import train
    from tests.conftest import make_train_cfg

    # Rebuild the tiny config here (not via fixture) so `regen` works as a
    # plain script.
    from dtc_tpu.config.schema import ModelConfig, OptimConfig

    kw = dict(overrides)
    model_cfg = ModelConfig(
        vocab_size=97, d_model=64, n_layers=4, n_heads=4, d_ff=128,
        max_seq_len=32, dropout=0.0, param_dtype="float32",
        compute_dtype="float32", attention="dense",
        **kw.pop("model", {}),
    )
    opt_cfg = OptimConfig(lr=1e-3, weight_decay=0.1, grad_clip=1.0)
    if "mesh" in kw:
        kw["mesh"] = MeshConfig(**kw["mesh"])
    cfg = make_train_cfg(strategy if strategy != "moe" else "tp",
                         steps=GOLDEN_STEPS, **kw)
    res = train(cfg, model_cfg, opt_cfg)
    return [round(float(v), 6) for v in res.losses]


def _load_goldens() -> dict:
    with open(GOLDEN_PATH) as f:
        return json.load(f)


def test_golden_trajectories():
    goldens = _load_goldens()
    for strategy, overrides in GOLDEN_RUNS.items():
        losses = _run(strategy, overrides)
        expected = goldens[strategy]
        np.testing.assert_allclose(
            losses, expected, rtol=2e-3, atol=2e-3,
            err_msg=(
                f"{strategy} trajectory drifted from committed golden — if the "
                "numerics change was intentional, regenerate with "
                "`python tests/test_golden.py regen`"
            ),
        )


def test_flagship_init_loss_is_log_vocab():
    """Untrained flagship GPT-89.6M must score ~log(50258) = 10.825 on its
    first batch: logits at init are near-uniform over the (masked) vocab.
    Catches init-scale, vocab-padding-mask, and CE regressions in one number.
    """
    import jax
    import jax.numpy as jnp

    from dtc_tpu.config.schema import ModelConfig
    from dtc_tpu.data.synthetic import synthetic_batch_iterator
    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.train.train_step import cross_entropy_loss

    cfg = ModelConfig(
        vocab_size=50258, d_model=512, n_layers=12, n_heads=16, d_ff=2048,
        max_seq_len=128,  # shorter seq: same invariant, 4x cheaper on CPU
        dropout=0.1, param_dtype="float32", compute_dtype="float32",
        attention="dense",
    )
    model = GPT(cfg)
    tok = next(synthetic_batch_iterator(2, cfg.max_seq_len + 1, cfg.vocab_size))
    x, y = jnp.asarray(tok[:, :-1]), jnp.asarray(tok[:, 1:])
    params = jax.jit(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    )()["params"]
    loss = float(cross_entropy_loss(model.apply({"params": params}, x, train=False), y))
    expected = float(np.log(cfg.vocab_size))
    # For ~N(0, sigma^2) logits, E[CE] ~= log(V) + sigma^2/2; flax's default
    # lecun/normal inits give sigma^2 ~= 1.5 here (measured 11.60 vs
    # log V = 10.82). Anything past log(V) + 1 means broken init scale, a
    # vocab-padding-mask leak, or a CE regression.
    assert expected - 0.1 < loss < expected + 1.0, (
        f"init loss {loss} vs log(vocab) {expected}"
    )


def regen() -> None:
    goldens = {s: _run(s, o) for s, o in GOLDEN_RUNS.items()}
    with open(GOLDEN_PATH, "w") as f:
        json.dump(goldens, f, indent=1)
    print(f"wrote {GOLDEN_PATH}")
    for s, v in goldens.items():
        print(s, v)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        import tests.conftest  # noqa: F401  (forces the 8-device CPU mesh)

        regen()
    else:
        print(__doc__)
