"""Multi-tenant LoRA adapters (ISSUE 10): injection numerics, adapter-only
training/checkpointing, and batched multi-tenant serving.

Anchor invariants:

- rank 0 is BITWISE off (no "lora" collection, logits byte-identical to a
  pre-adapter model);
- the runtime adapter path (base matmul + low-rank delta) decodes
  token-exactly against the offline merged-weights oracle
  (``W' = W + (alpha/r)·A·B`` through a plain model);
- training moves ONLY the adapter subtree (the frozen base is bitwise
  untouched), and a chaos-injected finetune is bit-identical to a clean
  one — the PR 2 acceptance bar, re-proven for the adapter TrainState;
- K co-scheduled tenants in ONE serving batch each decode token-identical
  to their solo runs, recompile-free across adapter loads + admissions.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dtc_tpu.adapters import (
    AdapterStore,
    gather_slot_lora,
    init_lora,
    init_lora_stack,
    load_adapter_file,
    merge_lora,
    save_adapter,
)
from dtc_tpu.config.schema import (
    AdapterConfig,
    ChaosConfig,
    ModelConfig,
    ResilienceConfig,
    ServeConfig,
)
from dtc_tpu.generate import generate
from dtc_tpu.models.gpt import GPT, adapter_param_count, param_count
from dtc_tpu.serve import (
    AdapterStoreFullError,
    Request,
    RequestState,
    ServingEngine,
    UnknownAdapterError,
)

VOCAB = 97

_BASE_KW = dict(
    vocab_size=VOCAB, d_model=64, n_layers=4, n_heads=4, d_ff=128,
    max_seq_len=32, dropout=0.0, param_dtype="float32",
    compute_dtype="float32", attention="dense",
)


def _rand_lora(model, seed, scale=0.05):
    """Random NONZERO factors (init_lora's B is zero by design — fine for
    shapes, useless for numerics tests)."""
    base = init_lora(model, 0)
    leaves, td = jax.tree.flatten(base)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    return jax.tree.unflatten(td, [
        scale * jax.random.normal(k, l.shape, l.dtype)
        for k, l in zip(keys, leaves)
    ])


@pytest.fixture(scope="module")
def lora_setup():
    """One adapter-enabled tiny GPT + its plain twin + base params + two
    nonzero factor trees, shared by every test in the module."""
    cfg = ModelConfig(**_BASE_KW, adapter=AdapterConfig(rank=4, alpha=8.0))
    plain_cfg = ModelConfig(**_BASE_KW)
    model, plain = GPT(cfg), GPT(plain_cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.ones((1, 1), jnp.int32),
        train=False,
    )
    return {
        "cfg": cfg, "plain_cfg": plain_cfg, "model": model, "plain": plain,
        "params": variables["params"], "lora0": variables["lora"],
        "lA": _rand_lora(model, 11), "lB": _rand_lora(model, 22),
    }


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=n).tolist() for n in sizes]


# ---------------------------------------------------------------------------
# config + host-side units
# ---------------------------------------------------------------------------

def test_adapter_config_validation():
    with pytest.raises(ValueError):
        AdapterConfig(rank=-1)
    with pytest.raises(ValueError):
        AdapterConfig(rank=4, alpha=0.0)
    with pytest.raises(ValueError):
        AdapterConfig(rank=4, dropout=1.0)
    with pytest.raises(ValueError):
        AdapterConfig(rank=4, target_modules=("q_proj", "wte"))
    with pytest.raises(ValueError):
        AdapterConfig(rank=4, target_modules=())
    assert AdapterConfig(rank=8, alpha=16.0).scale == 2.0
    assert AdapterConfig().scale == 0.0
    with pytest.raises(ValueError):
        ServeConfig(max_adapters=1)
    # YAML hands over lists; the config must coerce to tuple so the model
    # config stays hashable (generate() jits with the model static).
    cfg = AdapterConfig(rank=2, target_modules=["q_proj", "fc1"])
    assert cfg.target_modules == ("q_proj", "fc1")
    hash(ModelConfig(**_BASE_KW, adapter=cfg))
    # MoE has no dense fc1/fc2: an adapter targeting only them would have
    # ZERO sites — rejected at config time, not as a downstream KeyError.
    with pytest.raises(ValueError, match="attention"):
        ModelConfig(
            **_BASE_KW, moe_experts=4,
            adapter=AdapterConfig(rank=4, target_modules=("fc1", "fc2")),
        )
    # Attention targets + MoE is fine.
    ModelConfig(
        **_BASE_KW, moe_experts=4,
        adapter=AdapterConfig(rank=4, target_modules=("q_proj",)),
    )


def test_adapter_store_lru_refcounts_and_typed_full():
    s = AdapterStore(capacity=3)  # slot 0 base + 2 tenant slots
    slot_a, ev = s.register("a")
    assert slot_a == 1 and ev is None
    slot_b, ev = s.register("b")
    assert slot_b == 2 and ev is None
    # Re-register = same slot (hot update), no eviction.
    assert s.register("a") == (1, None)
    # "b" is now LRU; a third tenant evicts it.
    slot_c, ev = s.register("c")
    assert slot_c == 2 and ev == "b"
    assert s.slot_of("b") is None and s.slot_of("c") == 2
    # Refcounts pin residency: with both tenants held, the store is full.
    s.acquire("a"), s.acquire("c")
    with pytest.raises(AdapterStoreFullError):
        s.register("d")
    # Hot-updating a PINNED tenant's factors would fork its in-flight
    # decode from the KV already computed — caller bug, ValueError.
    with pytest.raises(ValueError, match="in-flight"):
        s.register("a")
    s.release("c")
    slot_d, ev = s.register("d")
    assert slot_d == 2 and ev == "c"
    with pytest.raises(ValueError):
        s.register("base")
    with pytest.raises(KeyError):
        s.acquire("ghost")


def test_adapter_param_count_and_collection_shapes(lora_setup):
    cfg, lora0 = lora_setup["cfg"], lora_setup["lora0"]
    n = sum(l.size for l in jax.tree.leaves(lora0))
    assert n == adapter_param_count(cfg)
    # Counted separately: the base count is the pre-adapter count.
    assert param_count(cfg) == param_count(lora_setup["plain_cfg"])
    # Stacked per layer: every factor leaf leads with the layers axis.
    for leaf in jax.tree.leaves(lora0):
        assert leaf.shape[0] == cfg.n_layers
    # Disabled / attention-only accounting.
    assert adapter_param_count(lora_setup["plain_cfg"]) == 0
    attn_only = dataclasses.replace(
        cfg, adapter=AdapterConfig(rank=4, target_modules=("q_proj",))
    )
    assert adapter_param_count(attn_only) == cfg.n_layers * 4 * 128


def test_decode_metrics_gain_lora_terms(lora_setup):
    from dtc_tpu.utils.metrics import decode_step_bytes, decode_step_flops

    cfg, plain_cfg = lora_setup["cfg"], lora_setup["plain_cfg"]
    b, cache_len = 8, 16
    n_ad = adapter_param_count(cfg)
    assert decode_step_flops(cfg, b, cache_len) == pytest.approx(
        decode_step_flops(plain_cfg, b, cache_len) + 2.0 * n_ad * b
    )
    with_l = decode_step_bytes(cfg, b, cache_len)
    without = decode_step_bytes(plain_cfg, b, cache_len)
    assert with_l["lora"] == n_ad * 4 * b  # fp32 factors, per-row reads
    assert without["lora"] == 0.0
    assert with_l["total"] == pytest.approx(without["total"] + n_ad * 4 * b)
    # The per-tenant term scales with batch (no cross-row amortization).
    assert decode_step_bytes(cfg, 64, cache_len)["lora"] == n_ad * 4 * 64


# ---------------------------------------------------------------------------
# injection numerics
# ---------------------------------------------------------------------------

def test_rank0_is_bitwise_pristine(lora_setup):
    """A rank-0 adapter config creates no collection and changes no byte
    of the computation — the compiled model IS the pre-adapter model."""
    plain = lora_setup["plain"]
    r0 = GPT(ModelConfig(**_BASE_KW, adapter=AdapterConfig(rank=0)))
    x = jnp.asarray(_prompts(0, (8,))[0], jnp.int32)[None]
    k = jax.random.PRNGKey(0)
    vp = plain.init({"params": k}, x, train=False)
    v0 = r0.init({"params": k}, x, train=False)
    assert "lora" not in v0
    for a, b in zip(jax.tree.leaves(vp), jax.tree.leaves(v0)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    lp = np.asarray(plain.apply(vp, x, train=False))
    l0 = np.asarray(r0.apply(v0, x, train=False))
    assert np.array_equal(lp, l0), "rank-0 adapter config is not bitwise off"


def test_zero_init_and_missing_collection_equal_base(lora_setup):
    """B initializes to zero => the injected model starts AT the base; and
    applying an adapter-enabled model WITHOUT a lora collection is base
    semantics (generate/eval on bare base params just works)."""
    s = lora_setup
    x = jnp.asarray(_prompts(1, (9,))[0], jnp.int32)[None]
    base_logits = np.asarray(s["plain"].apply(
        {"params": s["params"]}, x, train=False
    ))
    zero_logits = np.asarray(s["model"].apply(
        {"params": s["params"], "lora": s["lora0"]}, x, train=False
    ))
    nolora_logits = np.asarray(s["model"].apply(
        {"params": s["params"]}, x, train=False
    ))
    assert np.array_equal(base_logits, zero_logits)
    assert np.array_equal(base_logits, nolora_logits)


def test_merged_weights_oracle_token_exact(lora_setup):
    """The runtime adapter path vs base weights merged OFFLINE
    (W' = W + scale·A·B applied through the PLAIN model): greedy decode
    must agree token-for-token."""
    s = lora_setup
    merged_params = merge_lora(s["params"], s["lA"], s["cfg"])
    changed_any = False
    for i, prompt in enumerate(_prompts(2, (6, 9))):
        p = jnp.asarray(prompt, jnp.int32)[None]
        runtime = np.asarray(generate(
            s["model"], s["params"], p, 8, lora=s["lA"]
        ))
        merged = np.asarray(generate(s["plain"], merged_params, p, 8))
        assert (runtime == merged).all(), f"prompt {i}: {runtime} vs {merged}"
        base = np.asarray(generate(s["plain"], s["params"], p, 8))
        changed_any |= not (runtime == base).all()
    # The adapter is no-op-proof: on at least one prompt it moves the
    # greedy argmax away from the base model's (per-prompt agreement is
    # legitimate at small delta scale).
    assert changed_any


def test_gathered_stack_matches_per_tenant_solo(lora_setup):
    """The serving primitive: a (n_adapters, ...) stack gathered per-row
    must produce, row by row, the same logits as per-tenant solo applies
    (row factors (B, in, r) vs shared factors (in, r))."""
    from dtc_tpu.generate import decode_step, init_cache

    s = lora_setup
    stack = init_lora_stack(s["model"], 3)
    stack = jax.tree.map(lambda st, l: st.at[1].set(l), stack, s["lA"])
    stack = jax.tree.map(lambda st, l: st.at[2].set(l), stack, s["lB"])
    prompt = jnp.asarray(_prompts(3, (7,))[0], jnp.int32)[None]
    batch = jnp.concatenate([prompt, prompt, prompt], axis=0)
    gathered = gather_slot_lora(stack, jnp.asarray([0, 1, 2], jnp.int32))
    _, logits = decode_step(
        s["model"], s["params"], init_cache(s["model"], 3), batch, gathered
    )
    solos = [
        s["plain"].apply({"params": s["params"]}, prompt, train=False),
        s["model"].apply(
            {"params": s["params"], "lora": s["lA"]}, prompt, train=False
        ),
        s["model"].apply(
            {"params": s["params"], "lora": s["lB"]}, prompt, train=False
        ),
    ]
    for row, solo in enumerate(solos):
        np.testing.assert_allclose(
            np.asarray(logits[row]), np.asarray(solo[0]), atol=1e-5
        )


def test_adapter_artifact_roundtrip(lora_setup, tmp_path):
    s = lora_setup
    path = str(tmp_path / "t.npz")
    save_adapter(path, s["lA"], {"rank": 4, "name": "t"})
    tree, meta = load_adapter_file(path, like=s["lA"])
    assert meta["rank"] == 4 and meta["name"] == "t"
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(s["lA"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    # Wrong-shape factors are rejected loudly by the engine-side check.
    from dtc_tpu.adapters import validate_lora_tree

    stack = init_lora_stack(s["model"], 2)
    bad = jax.tree.map(lambda l: l[..., :-1], s["lA"])
    with pytest.raises(ValueError):
        validate_lora_tree(stack, bad)


# ---------------------------------------------------------------------------
# training leg
# ---------------------------------------------------------------------------

def test_lora_train_step_updates_only_adapter(lora_setup, train_cfg_factory,
                                              opt_cfg):
    """Two adapter train steps: the optimizer state and gradients live on
    the lora subtree alone; the frozen base is bitwise untouched."""
    from flax import linen as nn

    from dtc_tpu.parallel.mesh import mesh_from_config
    from dtc_tpu.parallel.sharding import DEFAULT_RULES
    from dtc_tpu.train.train_step import Batch, create_train_step
    from dtc_tpu.train.trainer import init_adapter_state

    s = lora_setup
    tc = train_cfg_factory("dp")
    mesh = mesh_from_config("dp", tc.mesh)
    with mesh, nn.logical_axis_rules(DEFAULT_RULES):
        state, base = init_adapter_state(
            s["model"], s["cfg"], tc, opt_cfg, mesh
        )
        base_before = jax.tree.map(lambda a: np.asarray(a).copy(), base)
        lora_before = jax.tree.map(
            lambda a: np.asarray(a).copy(), state.params
        )
        step = create_train_step(
            mesh, model=s["model"], state=state, base_params=base
        )
        x = jnp.zeros((tc.batch, s["cfg"].max_seq_len), jnp.int32)
        for i in range(2):
            state, loss = step(state, Batch(x=x, y=x), jax.random.PRNGKey(i))
        assert np.isfinite(float(loss))
    # Optimizer state mirrors the lora tree (AdamW moments per lora leaf).
    assert (
        jax.tree.structure(state.params)
        == jax.tree.structure(state.opt_state[1][0].mu)
    )
    moved = [
        not np.array_equal(np.asarray(a), b)
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(lora_before))
    ]
    assert all(moved), "some adapter factors never received an update"
    for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(base_before)):
        assert np.array_equal(np.asarray(a), b), "frozen base moved"


def test_adapter_checkpoint_subtree_restores_against_fresh_base(
    lora_setup, tmp_path
):
    """The CheckpointManager subtree contract: an adapter-only checkpoint
    written with ``subtree=("lora",)`` restores into a FRESHLY-initialized
    enclosing state — the frozen base is neither written to disk nor
    required by restore (restoring the full tree from it fails)."""
    from dtc_tpu.utils.checkpoint import CheckpointManager

    s = lora_setup
    full = {"params": s["params"], "lora": s["lA"]}
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(3, full, subtree=("lora",))
    # A fresh base + zeroed adapter slot stands in for a new process.
    fresh = {
        "params": s["params"],
        "lora": jax.tree.map(jnp.zeros_like, s["lA"]),
    }
    restored, step = mgr.restore_latest(fresh, subtree=("lora",))
    assert step == 3
    for a, b in zip(jax.tree.leaves(restored["lora"]),
                    jax.tree.leaves(s["lA"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert restored["params"] is fresh["params"]  # untouched passthrough
    # The checkpoint holds ONLY the adapter: a full-tree restore fails.
    with pytest.raises(FileNotFoundError):
        mgr.restore_latest(full)
    mgr.close()


def test_chaos_lora_finetune_bit_identical(train_cfg_factory, opt_cfg,
                                           tmp_path):
    """THE training-leg acceptance (ISSUE 10): a chaos-injected LoRA
    finetune (NaN-poisoned adapter at step 3 -> guard rollback to the
    adapter-only verified checkpoint -> stream re-seek -> replay) produces
    losses IDENTICAL to an uninjected finetune — the PR 2 guarantee,
    re-proven with the TrainState being the adapter subtree."""
    from dtc_tpu.train.trainer import train

    model_cfg = ModelConfig(**{**_BASE_KW, "dropout": 0.1},
                            adapter=AdapterConfig(rank=4, alpha=8.0))
    base = dict(steps=5, warmup_steps=1, log_every=1, checkpoint_every=2)
    clean = train(
        train_cfg_factory(
            "dp", output_dir=str(tmp_path / "clean"),
            checkpoint_dir=str(tmp_path / "clean_ckpt"), **base,
        ),
        model_cfg, opt_cfg,
    )
    chaotic = train(
        dataclasses.replace(
            train_cfg_factory(
                "dp", output_dir=str(tmp_path / "chaos"),
                checkpoint_dir=str(tmp_path / "chaos_ckpt"), **base,
            ),
            resilience=ResilienceConfig(
                chaos=ChaosConfig(enabled=True, nan_at_step=3)
            ),
        ),
        model_cfg, opt_cfg,
    )
    assert len(chaotic.losses) == 5
    np.testing.assert_allclose(chaotic.losses, clean.losses, rtol=1e-6)
    # The frozen base is identical across runs (it is seed-derived and
    # never updated, checkpointed, or rolled back).
    for a, b in zip(jax.tree.leaves(clean.base_params),
                    jax.tree.leaves(chaotic.base_params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_lora_under_pp_raises(train_cfg_factory, opt_cfg):
    from dtc_tpu.train.trainer import train

    model_cfg = ModelConfig(**_BASE_KW, adapter=AdapterConfig(rank=2))
    with pytest.raises(ValueError, match="pipeline"):
        train(train_cfg_factory("pp", pp_microbatches=2), model_cfg, opt_cfg)


# ---------------------------------------------------------------------------
# serving leg
# ---------------------------------------------------------------------------

def _engine(s, **kw):
    cfg = dict(slots=3, page_size=4, queue_depth=8, max_new_tokens=6,
               prefill_bucket=8, max_adapters=4)
    cfg.update(kw)
    return ServingEngine(s["model"], s["params"], ServeConfig(**cfg))


def test_mixed_batch_tenants_token_identical_to_solo(lora_setup):
    """K=3 co-scheduled tenants (two adapters + base) in ONE in-flight
    batch: each completes token-identical to its solo run."""
    s = lora_setup
    prompts = _prompts(4, (5, 7, 6))
    refs = [
        np.asarray(generate(
            s["model"], s["params"],
            jnp.asarray(prompts[0], jnp.int32)[None], 6, lora=s["lA"],
        ))[0].tolist(),
        np.asarray(generate(
            s["model"], s["params"],
            jnp.asarray(prompts[1], jnp.int32)[None], 6, lora=s["lB"],
        ))[0].tolist(),
        np.asarray(generate(
            s["model"], s["params"],
            jnp.asarray(prompts[2], jnp.int32)[None], 6,
        ))[0].tolist(),
    ]
    eng = _engine(s)
    eng.load_adapter("tA", s["lA"])
    eng.load_adapter("tB", s["lB"])
    eng.submit(Request(rid="a", prompt=prompts[0], max_new_tokens=6,
                       adapter="tA"))
    eng.submit(Request(rid="b", prompt=prompts[1], max_new_tokens=6,
                       adapter="tB"))
    eng.submit(Request(rid="c", prompt=prompts[2], max_new_tokens=6))
    res = eng.run(max_steps=200)
    for rid, ref in zip("abc", refs):
        assert res[rid].state is RequestState.DONE
        assert res[rid].tokens == ref, rid
    # All three decoded together at least once (continuous batching).
    assert eng.reg.histogram("serve_batch_occupancy").max == 3
    # Per-tenant SLO surface exists.
    snap = eng.reg.snapshot()
    for tenant in ("tA", "tB", "base"):
        assert f"serve_ttft_s.{tenant}" in snap
    # serve_request events carry the adapter name.
    assert res["a"].adapter == "tA" and res["c"].adapter is None


def test_unknown_adapter_and_store_full_typed(lora_setup):
    s = lora_setup
    eng = _engine(s, max_adapters=2)  # base + ONE tenant slot
    with pytest.raises(UnknownAdapterError):
        eng.submit(Request(rid="x", prompt=[1, 2], max_new_tokens=2,
                           adapter="ghost"))
    eng.load_adapter("tA", s["lA"])
    eng.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=6,
                       adapter="tA"))
    # "tA" has an in-flight request: nothing is evictable.
    with pytest.raises(AdapterStoreFullError):
        eng.load_adapter("tB", s["lB"])
    eng.run(max_steps=100)
    # Terminal => unpinned => LRU eviction frees the slot.
    eng.load_adapter("tB", s["lB"])
    assert eng.adapter_store.slot_of("tA") is None
    assert eng.reg.snapshot()["adapter_evictions"] == 1
    # A lora-free engine rejects adapter requests and loads, typed.
    plain_eng = ServingEngine(s["plain"], s["params"], ServeConfig(
        slots=1, page_size=4, queue_depth=4, max_new_tokens=4,
        prefill_bucket=8,
    ))
    with pytest.raises(UnknownAdapterError):
        plain_eng.submit(Request(rid="y", prompt=[1], max_new_tokens=2,
                                 adapter="tA"))
    with pytest.raises(ValueError, match="lora-free"):
        plain_eng.load_adapter("tA", s["lA"])


def test_prefix_store_scoped_per_adapter(lora_setup):
    """The same system-prompt prefix under two tenants must NOT share KV
    (different adapters => different bytes): two store builds, and each
    tenant's own repeat admission hits its entry."""
    s = lora_setup
    rng = np.random.RandomState(9)
    prefix = rng.randint(0, VOCAB, size=6).tolist()
    tails = [rng.randint(0, VOCAB, size=3).tolist() for _ in range(4)]
    eng = _engine(s, slots=2)
    eng.load_adapter("tA", s["lA"])
    eng.load_adapter("tB", s["lB"])
    for i, (tail, ad) in enumerate(zip(tails, ("tA", "tA", "tB", "tB"))):
        eng.submit(Request(
            rid=f"r{i}", prompt=prefix + tail, max_new_tokens=4,
            adapter=ad, shared_prefix_len=len(prefix),
        ))
    res = eng.run(max_steps=300)
    snap = eng.reg.snapshot()
    assert snap["serve_prefix_builds"] == 2  # one per tenant, not one total
    assert snap["serve_prefix_hits"] == 2    # each tenant's second request
    # And the outputs are still per-tenant exact.
    for i, (tail, lora) in enumerate(zip(tails, (s["lA"], s["lA"], s["lB"],
                                                 s["lB"]))):
        ref = np.asarray(generate(
            s["model"], s["params"],
            jnp.asarray(prefix + tail, jnp.int32)[None], 4, lora=lora,
        ))[0].tolist()
        assert res[f"r{i}"].tokens == ref, i


def test_adapter_reload_invalidates_stale_prefix_kv(lora_setup):
    """A hot adapter update (reload under the same name) must drop prefix
    KV built under the OLD factors — a stale hit would decode the suffix
    under new factors against old-prefix bytes, silently wrong."""
    s = lora_setup
    rng = np.random.RandomState(13)
    prefix = rng.randint(0, VOCAB, size=6).tolist()
    tail = rng.randint(0, VOCAB, size=3).tolist()
    eng = _engine(s, slots=2)
    eng.load_adapter("t", s["lA"])
    eng.submit(Request(rid="r1", prompt=prefix + tail, max_new_tokens=4,
                       adapter="t", shared_prefix_len=len(prefix)))
    eng.run(max_steps=100)
    eng.load_adapter("t", s["lB"])  # hot update: lA -> lB
    eng.submit(Request(rid="r2", prompt=prefix + tail, max_new_tokens=4,
                       adapter="t", shared_prefix_len=len(prefix)))
    res = eng.run(max_steps=100)
    ref = np.asarray(generate(
        s["model"], s["params"], jnp.asarray(prefix + tail, jnp.int32)[None],
        4, lora=s["lB"],
    ))[0].tolist()
    assert res["r2"].tokens == ref, "stale prefix KV survived the reload"
    snap = eng.reg.snapshot()
    assert snap["serve_prefix_builds"] == 2  # rebuilt after the reload
    # Hot update while the tenant is in flight is refused, typed.
    eng.submit(Request(rid="r3", prompt=prefix + tail, max_new_tokens=6,
                       adapter="t", shared_prefix_len=len(prefix)))
    with pytest.raises(ValueError, match="in-flight"):
        eng.load_adapter("t", s["lA"])
    eng.run(max_steps=100)
    # Store-LRU eviction retires the tenant's per-name instruments.
    eng.load_adapter("u1", s["lA"])
    eng.load_adapter("u2", s["lB"])
    eng.load_adapter("u3", s["lA"])  # evicts "t" (max_adapters=4: 3 slots)
    assert eng.adapter_store.slot_of("t") is None
    snap = eng.reg.snapshot()
    assert "serve_ttft_s.t" not in snap
    assert "serve_ms_per_token.t" not in snap


def test_mixed_tenant_serving_never_recompiles(lora_setup):
    """The serve_decode audit invariant, live: adapter load + mixed-tenant
    admission + slot churn reuse ONE decode executable."""
    from dtc_tpu.obs.stepclock import CompileWatcher

    s = lora_setup
    prompts = _prompts(5, (5, 6, 4))
    eng = _engine(s)
    eng.load_adapter("tA", s["lA"])
    eng.submit(Request(rid="warm", prompt=[1, 2, 3], max_new_tokens=3,
                       adapter="tA"))
    eng.run(max_steps=30)
    w = CompileWatcher().activate()
    try:
        w.drain()
        eng.load_adapter("tB", s["lB"])  # hot load inside the window
        eng.submit(Request(rid="a", prompt=prompts[0], max_new_tokens=6,
                           adapter="tB"))
        eng.step()
        eng.submit(Request(rid="b", prompt=prompts[1], max_new_tokens=6))
        eng.step()  # mixed tenant+base batch mid-flight
        eng.submit(Request(rid="c", prompt=prompts[2], max_new_tokens=4,
                           adapter="tA"))
        eng.run(max_steps=150)  # slot reuse across three tenants
        _, steady = w.drain()
    finally:
        w.deactivate()
    assert steady == 0, f"{steady} recompile(s) across adapter churn"


def test_chaos_mixed_tenant_acceptance_with_eviction(lora_setup):
    """THE serving-leg acceptance (ISSUE 10): mixed-tenant serving under a
    binding page pool (eviction + re-prefill) with injected preemption,
    KV-page corruption, and poisoned logits — every completed request is
    token-identical to the clean run, per tenant; the doomed request ends
    typed. No silent drops."""
    from dtc_tpu.obs import MemorySink

    s = lora_setup
    prompts = _prompts(6, (6, 8, 5, 7))
    adapters = ("tA", "tB", None, "tA")

    def build(chaos):
        eng = _engine(
            s, slots=2, total_pages=8, max_new_tokens=8,
            verify_pages_every=1, chaos=chaos or ChaosConfig(),
        )
        eng.load_adapter("tA", s["lA"])
        eng.load_adapter("tB", s["lB"])
        return eng

    def drive(eng, with_doomed):
        for i, (p, ad) in enumerate(zip(prompts, adapters)):
            eng.submit(Request(rid=f"c{i}", prompt=p, max_new_tokens=8,
                               adapter=ad))
        if with_doomed:
            eng.submit(Request(rid="doomed", prompt=[1, 2, 3],
                               max_new_tokens=8, deadline_s=1e-9))
        return eng.run(max_steps=600)

    clean = drive(build(None), with_doomed=False)
    chaos = ChaosConfig(
        enabled=True, serve_preempt_at_step=4, serve_corrupt_page_at_step=6,
        serve_poison_logits_at_step=8,
    )
    eng = build(chaos)
    sink = eng.reg.add_sink(MemorySink())
    faulted = drive(eng, with_doomed=True)

    snap = eng.reg.snapshot()
    assert snap["chaos_injections"] == 3
    assert snap["serve_preemptions"] == 1
    assert snap["serve_corruptions"] == 1
    assert snap["serve_retries"] >= 1
    assert sum(r.n_evictions for r in faulted.values()) > 0
    for i in range(len(prompts)):
        rid = f"c{i}"
        assert faulted[rid].state is RequestState.DONE
        assert faulted[rid].tokens == clean[rid].tokens, rid
    from dtc_tpu.serve import DeadlineExceededError

    assert faulted["doomed"].state is RequestState.EXPIRED
    assert isinstance(faulted["doomed"].error, DeadlineExceededError)
    terminal = [e for e in sink.events if e["etype"] == "serve_request"]
    assert sorted(e["rid"] for e in terminal) == sorted(faulted)
    # Pool fully reclaimed; adapter pins all released.
    assert eng.alloc.free_pages == eng.alloc.total_pages
    assert eng.adapter_store.refcount("tA") == 0
    assert eng.adapter_store.refcount("tB") == 0
