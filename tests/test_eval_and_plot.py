"""Periodic eval wiring + plot.py end-to-end (round-2 VERDICT "dead corners")."""

import os

import numpy as np

from dtc_tpu.config.schema import MeshConfig
from tests.conftest import make_train_cfg


def test_eval_runs_and_is_finite(tiny_model_cfg, opt_cfg, tmp_path):
    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "dp", steps=4, eval_every=2, eval_batches=2, output_dir=str(tmp_path)
    )
    res = train(cfg, tiny_model_cfg, opt_cfg)
    steps = [s for s, _ in res.eval_losses]
    assert steps == [2, 4]
    assert all(np.isfinite(v) for _, v in res.eval_losses)
    # Eval loss at a tiny-vocab init sits near log(vocab); after 4 steps it
    # must still be in a sane band.
    assert 0 < res.eval_losses[-1][1] < 10
    assert os.path.exists(tmp_path / "eval_log.csv")
    rows = (tmp_path / "eval_log.csv").read_text().strip().splitlines()
    assert rows[0] == "step,loss" and len(rows) == 3


def test_eval_works_under_pp(tiny_model_cfg, opt_cfg):
    """Eval unstacks pipeline params and runs the GSPMD forward."""
    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "pp", steps=2, eval_every=2, eval_batches=1, pp_microbatches=2,
        mesh=MeshConfig(pipe=2, data=4, model=1),
    )
    res = train(cfg, tiny_model_cfg, opt_cfg)
    assert len(res.eval_losses) == 1 and np.isfinite(res.eval_losses[0][1])


def test_eval_loss_matches_manual_forward(tiny_model_cfg, opt_cfg):
    """The wired eval path computes the same number as a hand-rolled
    dropout-free forward pass on the same batches."""
    import jax

    from dtc_tpu.models.gpt import GPT
    from dtc_tpu.train.train_step import cross_entropy_loss
    from dtc_tpu.train.trainer import make_eval_iterator, train

    cfg = make_train_cfg("dp", steps=2, eval_every=2, eval_batches=2)
    res = train(cfg, tiny_model_cfg, opt_cfg)
    model = GPT(tiny_model_cfg)
    it = make_eval_iterator(cfg, tiny_model_cfg)
    vals = []
    params = jax.device_get(res.state.params)
    for _ in range(2):
        tok = next(it)
        logits = model.apply({"params": params}, tok[:, :-1], train=False)
        vals.append(float(cross_entropy_loss(logits, tok[:, 1:])))
    np.testing.assert_allclose(res.eval_losses[-1][1], np.mean(vals), rtol=1e-5)


def test_plot_end_to_end(tmp_path):
    """plot.py consumes the reference CSV schema and writes both PNGs."""
    import plot

    for s, offs in (("dp", 0.0), ("tp", 0.01), ("pp", 0.02), ("3d", 0.03)):
        d = tmp_path / s
        d.mkdir()
        with open(d / "log.csv", "w") as f:
            f.write("step,elapsed_time,loss\n")
            for i in range(1, 51):
                f.write(f"{i},{i * 0.1 + offs},{5.0 / i + offs}\n")
    plot.main(str(tmp_path))
    assert (tmp_path / "loss.png").exists()
    assert (tmp_path / "average_elapsed_time.png").exists()


def test_profiler_window_captures_trace(tiny_model_cfg, opt_cfg, tmp_path):
    """profile_start/profile_stop capture a trace for exactly that step
    window (the last public trainer surface without a test)."""
    import glob

    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "dp", steps=4, profile_start=2, profile_stop=4,
        output_dir=str(tmp_path),
    )
    train(cfg, tiny_model_cfg, opt_cfg)
    traces = glob.glob(str(tmp_path / "profile" / "**" / "*.trace.json.gz"), recursive=True)
    assert traces, "no trace captured in the configured window"
