"""Device-time observatory tests (ISSUE 8).

The parser/attribution tests run against the COMMITTED fixture capture
(``tests/fixtures/devprof_capture/`` — a hand-built trace.json.gz + meta
sidecar with hand-computed durations), never against live profiler
output: this environment's test harness disables the CPU thunk runtime
(``--xla_cpu_use_thunk_runtime=false``, see conftest), under which the
profiler emits no per-op events at all. The capture-window tests
therefore assert the MECHANICS (window lifecycle, meta sidecar, trigger
wiring, warn-not-fail on empty captures); the full capture->attribute
pipeline is exercised by ``scripts/devprof_smoke.py`` (tier-1 pre-gate),
which runs with the default thunk runtime where op events exist.
"""

import glob
import importlib
import json
import os
import sys
import warnings

import pytest

from dtc_tpu.obs import devprof

FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "devprof_capture"
)

# Hand-computed fixture facts (see the generator comments in the fixture):
# rows (self-time ms): fusion.1=10 (attn_qkv, fwd), fusion.2=5 (mlp, bwd),
# fusion.4=9-4=5 (optimizer; fusion.5 nests inside), fusion.5=4 (optimizer),
# copy.9=2 (data_movement), dot.11=3 (scope-less), all-reduce.7=8
# (collectives, tid 2). Umbrella events jit_train_step + "5" skipped.
TOTAL_S = 0.037
UNATTRIBUTED_S = 0.003


def load_fixture_rows():
    path = devprof.find_trace_file(FIXTURE)
    assert path, "committed fixture trace missing"
    return devprof.device_op_rows(devprof.load_trace(path))


# ---------------------------------------------------------------------------
# parser


class TestParser:
    def test_selection_skips_umbrellas_and_host(self):
        rows = load_fixture_rows()
        names = {r.name for r in rows}
        assert names == {
            "fusion.1", "fusion.2", "fusion.4", "fusion.5", "copy.9",
            "dot.11", "all-reduce.7",
        }
        # the host python thread's events never enter the device rows
        assert all(r.pid == 10 for r in rows)

    def test_typed_fields(self):
        rows = {r.name: r for r in load_fixture_rows()}
        r = rows["fusion.1"]
        assert r.hlo_module == "jit_train_step"
        assert r.t0_s == pytest.approx(0.001)
        assert r.dur_s == pytest.approx(0.010)
        assert r.kind == "compute"
        assert "attn_qkv" in r.scope
        assert rows["all-reduce.7"].kind == "collective"

    def test_cpu_fallback_selection(self):
        """A trace with NO device pid (the TFRT CPU backend) selects the
        XLA op events by their hlo_op arg instead."""
        trace = {"traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "X", "pid": 1, "tid": 3, "name": "dot.4", "ts": 100.0,
             "dur": 50.0, "args": {"hlo_op": "dot.4", "hlo_module": "jit_f"}},
            {"ph": "X", "pid": 1, "tid": 3, "name": "ThunkExecutor::Execute",
             "ts": 0.0, "dur": 500.0},  # no hlo_op arg: not an op event
        ]}
        rows = devprof.device_op_rows(trace)
        assert [r.name for r in rows] == ["dot.4"]
        assert rows[0].scope == ""  # CPU events carry no provenance args

    def test_self_times_nesting(self):
        rows = load_fixture_rows()
        selfs = dict(zip([r.name for r in rows], devprof.self_times(rows)))
        assert selfs["fusion.4"] == pytest.approx(0.005)  # 9ms - nested 4ms
        assert selfs["fusion.5"] == pytest.approx(0.004)
        assert selfs["fusion.1"] == pytest.approx(0.010)


# ---------------------------------------------------------------------------
# scope recovery + classification


class TestScopes:
    def test_scope_map_from_hlo(self):
        hlo = (
            'ENTRY %main {\n'
            '  %dot.11 = f32[8,97]{1,0} dot(%a, %b), '
            'metadata={op_name="jit(step)/jit(main)/jvp(fwd)/GPT/head/dot_general" '
            'source_file="x.py" source_line=1}\n'
            '  %add.1 = f32[] add(%c, %d)\n'
            "}\n"
        )
        m = devprof.scope_map_from_hlo(hlo)
        assert m == {
            "dot.11": "jit(step)/jit(main)/jvp(fwd)/GPT/head/dot_general"
        }

    def test_scope_for_strips_executor_suffixes(self):
        row = devprof.OpRow(
            name="tanh.5.clone", hlo_op="tanh.5.clone", hlo_module="m",
            scope="", t0_s=0.0, dur_s=1.0, pid=0, tid=0, kind="compute",
        )
        assert devprof.scope_for(row, {"tanh.5": "a/mlp/tanh"}) == "a/mlp/tanh"
        assert devprof.scope_for(row, {}) == ""

    @pytest.mark.parametrize("scope,component,phase", [
        ("jit(s)/jvp(fwd)/GPT/stage/blocks/attn/attn_qkv/dot", "attn_qkv", "fwd"),
        ("jit(s)/transpose(jvp(fwd))/GPT/stage/blocks/mlp/fc1/dot", "mlp", "bwd"),
        ("jit(s)/optimizer/mul", "optimizer", "optimizer"),
        ("jit(s)/jvp(GPT)/head/ln_f/rsqrt", "ln", "fwd"),  # inner wins
        ("jit(s)/jvp(GPT)/embed/wte/gather", "embed", "fwd"),
        ("jit(s)/jvp(fwd)/GPT/stage/while/body/blocks/Block_0/add",
         "residual", "fwd"),
        ("jit(s)/jvp(fwd)/GPT/stage/while/body/select_n", "scan", "fwd"),
        ("jit(generate)/prefill/GPT/stage/blocks/attn/attn_kernel/dot",
         "attn_kernel", ""),
        ("", "", ""),
    ])
    def test_classify_scope(self, scope, component, phase):
        assert devprof.classify_scope(scope) == (component, phase)


# ---------------------------------------------------------------------------
# attribution


class TestAttribution:
    def test_component_rollup_without_scope_map(self):
        att = devprof.attribute(load_fixture_rows())
        assert att.n_ops == 7
        assert att.total_s == pytest.approx(TOTAL_S)
        assert att.components["attn_qkv"] == pytest.approx(0.010)
        assert att.components["mlp"] == pytest.approx(0.005)
        assert att.components["optimizer"] == pytest.approx(0.009)
        assert att.components["data_movement"] == pytest.approx(0.002)
        assert att.components["collectives"] == pytest.approx(0.008)
        assert att.unattributed_s == pytest.approx(UNATTRIBUTED_S)
        assert att.attributed_share == pytest.approx(
            (TOTAL_S - UNATTRIBUTED_S) / TOTAL_S
        )
        assert att.phases == pytest.approx(
            {"fwd": 0.010, "bwd": 0.005, "optimizer": 0.009}
        )

    def test_overlap_and_busy(self):
        att = devprof.attribute(load_fixture_rows())
        assert att.collective_s == pytest.approx(0.008)
        assert att.compute_s == pytest.approx(0.029)
        # all-reduce [5,13]ms vs compute union: [5,11] + [12,13] = 7ms
        assert att.overlap_s == pytest.approx(0.007)
        assert att.overlap_ratio == pytest.approx(7 / 8)
        assert att.busy_s == pytest.approx(0.029)  # tid 1 self-time sum

    def test_scope_map_join_completes_attribution(self):
        sm = {"dot.11": "jit(s)/jit(main)/jvp(fwd)/GPT/head/dot_general"}
        att = devprof.attribute(load_fixture_rows(), scope_map=sm)
        assert att.components["head"] == pytest.approx(0.003)
        assert att.unattributed_s == 0.0
        assert att.attributed_share == pytest.approx(1.0)

    def test_component_table_and_mfu(self):
        att = devprof.attribute(load_fixture_rows())
        table = att.component_table(steps=2)
        assert table[0]["component"] == "attn_qkv"
        assert table[0]["s_per_step"] == pytest.approx(0.005)
        assert table[-1]["component"] == "(unattributed)"
        assert sum(r["share"] for r in table) == pytest.approx(1.0)
        # busy/step = 14.5ms; 1e9 FLOPs / (0.0145s * 1e12 FLOP/s)
        assert att.device_mfu(1.0e9, 1.0e12, steps=2) == pytest.approx(
            1.0e9 / (0.0145 * 1.0e12)
        )
        assert att.device_mfu(None, 1.0e12) is None
        assert att.device_mfu(1.0e9, None) is None

    def test_structural_gates(self):
        att = devprof.attribute(load_fixture_rows())
        g = devprof.structural_gates(att)
        assert g["all_dot_fusions_attributed"] is False
        assert g["unattributed_dot_fusions"] == ["dot.11"]
        assert g["unattributed_share_ok"] is True  # 3/37 < 10%
        sm = {"dot.11": "jit(s)/jvp(fwd)/GPT/head/dot_general"}
        g2 = devprof.structural_gates(
            devprof.attribute(load_fixture_rows(), scope_map=sm)
        )
        assert g2["all_dot_fusions_attributed"] is True
        assert g2["unattributed_share"] == 0.0

    def test_census_crosscheck_warn_band(self):
        att = devprof.attribute(load_fixture_rows())
        # 8/37 = 21.6% collective time vs a census that expects none
        assert devprof.census_crosscheck(att, {"total": 0.0})
        # a comm-heavy census with measured collectives: no warning
        assert devprof.census_crosscheck(att, {"total": 1e6}) == []
        # comm-heavy census but a capture with zero collective time
        compute_only = [r for r in load_fixture_rows() if r.kind == "compute"]
        att2 = devprof.attribute(compute_only)
        assert devprof.census_crosscheck(att2, {"total": 1e6})
        assert devprof.census_crosscheck(att2, {"total": 0.0}) == []


# ---------------------------------------------------------------------------
# merged export + capture-dir plumbing


class TestMergedExport:
    def test_wall_anchor_from_start_trace_marker(self):
        trace = devprof.load_trace(devprof.find_trace_file(FIXTURE))
        t0, wall = devprof.trace_wall_anchor(trace, 1000.0005)
        assert t0 == pytest.approx(0.0005)  # the start_trace event's ts
        assert wall == 1000.0005

    def test_analyze_capture_and_find_captures(self):
        caps = devprof.find_captures(os.path.dirname(FIXTURE))
        assert FIXTURE in caps
        res = devprof.analyze_capture(FIXTURE)
        assert res is not None
        assert res["meta"]["peak_hbm_bytes"] == 123456
        assert res["attribution"].n_ops == 7
        assert res["anchor"] == (pytest.approx(0.0005), 1000.0005)
        assert devprof.analyze_capture("/nonexistent/dir") is None

    def test_merged_chrome_trace_aligned(self):
        from dtc_tpu.obs.trace import to_chrome_trace

        res = devprof.analyze_capture(FIXTURE)
        dev = devprof.device_rows_to_events(res["rows"], anchor=res["anchor"])
        # fusion.1: trace t0=1ms, anchor trace 0.5ms -> wall 1000.001
        f1 = next(e for e in dev if e["name"] == "fusion.1")
        assert f1["t0"] == pytest.approx(1000.001)
        assert f1["component"] == "attn_qkv"
        assert f1["kind"] == "compute"
        host = [{
            "etype": "span", "name": "step", "cat": "train", "tid": "train",
            "ph": "X", "t0": 1000.0, "dur_s": 0.05, "proc": 0,
        }]
        merged = to_chrome_trace(host + dev)
        rows = [e for e in merged["traceEvents"] if e.get("cat") != "__metadata"]
        cats = {e["cat"] for e in rows}
        assert {"train", "device"} <= cats
        ts = [e["ts"] for e in rows]
        assert ts == sorted(ts)
        assert all({"name", "ph", "ts", "dur", "pid", "tid"} <= set(e) for e in rows)
        # one clock: the host span starts before the first device op and
        # the device ops land INSIDE its duration window
        host_row = next(e for e in rows if e["cat"] == "train")
        dev_ts = [e["ts"] for e in rows if e["cat"] == "device"]
        assert min(dev_ts) >= host_row["ts"]
        assert max(dev_ts) <= host_row["ts"] + host_row["dur"]


# ---------------------------------------------------------------------------
# profile_step.parse: byte-compatible --top output over the shared parser


class TestProfileStepParity:
    def test_parse_output_format(self, capsys):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        import profile_step

        profile_step.parse(FIXTURE, steps=2, top=3)
        out = capsys.readouterr().out.splitlines()
        assert out[0].startswith("# trace: ")
        assert out[1].startswith("# NOTE: rows are NOT additive")
        # RAW durations (not self-times) by event name, desc, /steps:
        # fusion.1 10ms, fusion.4 9ms, all-reduce.7 8ms over 2 steps.
        assert out[3] == f"{5.0:8.3f} ms/step  fusion.1"
        assert out[4] == f"{4.5:8.3f} ms/step  fusion.4"
        assert out[5] == f"{4.0:8.3f} ms/step  all-reduce.7"
        assert len(out) == 6  # --top honored


# ---------------------------------------------------------------------------
# capture windows (mechanics only — op events don't exist under the test
# harness's thunk-runtime flag; the devprof smoke covers the full path)


class TestCaptureWindows:
    def test_capture_window_meta_and_watermark(self, tmp_path):
        d = str(tmp_path / "cap")
        with devprof.CaptureWindow(
            d, steps=3, reason="unit", step_flops=1.0, peak_flops=2.0,
            comm_estimate={"total": 0.0},
        ) as cap:
            pass
        if not cap.ok:  # another test leaked an active profiler session
            pytest.skip("profiler session unavailable in this process")
        meta = devprof.load_meta(d)
        assert meta is not None
        assert meta["reason"] == "unit"
        assert meta["steps"] == 3
        assert meta["t_wall_stop"] >= meta["t_wall_start"]
        assert "peak_hbm_bytes" in meta  # explicit null on CPU
        assert meta["step_flops"] == 1.0

    def test_capture_tolerates_empty_environment(self, tmp_path):
        """The warn-not-fail contract: an environment where capture
        yields no op events (this harness) must not raise anywhere in
        the capture->analyze path."""
        d = str(tmp_path / "cap")
        with devprof.CaptureWindow(d, reason="empty") as cap:
            pass
        res = devprof.analyze_capture(d) if cap.ok else None
        if res is not None:
            att = res["attribution"]
            # no op rows -> empty-but-typed attribution, gates report not-ok
            assert att.total_s >= 0.0
            assert devprof.structural_gates(att)["unattributed_share_ok"] in (
                True, False,
            )

    def test_device_profiler_cadence_and_finalize(self, tmp_path):
        from dtc_tpu.obs import MemorySink, MetricsRegistry

        reg = MetricsRegistry()
        sink = reg.add_sink(MemorySink())
        dp = devprof.DeviceProfiler(
            str(tmp_path / "devprof"), registry=reg, every=3, n_steps=1,
        )
        for s in range(1, 6):
            dp.on_step(s)
        dp.close()
        if dp.disabled:
            pytest.skip("profiler session unavailable in this process")
        assert dp.captures == 1
        assert dp.last_artifact and os.path.isdir(dp.last_artifact)
        assert devprof.load_meta(dp.last_artifact)["reason"] == "cadence"
        evs = [e for e in sink.events if e["etype"] == "devprof"]
        assert len(evs) == 1 and evs[0]["reason"] == "cadence"

    def test_device_profiler_request_and_busy_defer(self, tmp_path):
        dp = devprof.DeviceProfiler(str(tmp_path / "devprof"), n_steps=1)
        assert dp.request("slo_breach:x") is True
        assert dp.request("second") is False  # one pending at a time
        dp.on_step(1, busy=True)  # legacy profiler window active: defer
        assert dp._prof is None and dp._pending == "slo_breach:x"
        dp.on_step(2)
        started = dp._prof is not None
        dp.on_step(3)
        dp.close()
        if dp.disabled and not dp.captures:
            pytest.skip("profiler session unavailable in this process")
        assert started
        assert dp.captures == 1
        assert "slo_breach" in devprof.load_meta(dp.last_artifact)["reason"]

    def test_telemetry_wiring(self, tmp_path):
        """Telemetry constructs the observatory, drives it from
        on_step_start, and the hung-step trigger arms a window."""
        from dtc_tpu.config.schema import ObsConfig
        from dtc_tpu.obs import Telemetry

        tele = Telemetry(
            ObsConfig(memory_sample_every=0, devprof_every=0),
            output_dir=str(tmp_path),
        )
        try:
            assert tele.devprof is not None  # devprof_on_trigger default
            tele.set_device_profile_context(
                step_flops=7.0, peak_flops=9.0, comm_estimate={"total": 1.0}
            )
            assert tele.devprof.step_flops == 7.0
            tele.on_hung_step(step=3)
            assert tele.devprof._pending == "hung_step"
            assert tele.request_device_profile() is False  # already pending
            tele.on_step_start(4)   # window opens (or warn-disables)
            tele.clock.end()
            tele.on_step_start(5)
            tele.clock.end()
            tele.on_step_start(6)
            tele.clock.end()
        finally:
            tele.close()
        if tele.devprof.disabled and not tele.devprof.captures:
            pytest.skip("profiler session unavailable in this process")
        assert tele.devprof.captures >= 1
        meta = devprof.load_meta(tele.devprof.last_artifact)
        assert meta["step_flops"] == 7.0
        assert meta["comm_estimate"] == {"total": 1.0}

    def test_slo_breach_trigger_is_edge_not_level(self, tmp_path):
        """A PERSISTENTLY breaching SLO arms exactly ONE capture (the
        objective entering the active set), not one per evaluation —
        else max_captures burns out on a single sustained breach."""
        from dtc_tpu.config.schema import ObsConfig, SloConfig
        from dtc_tpu.obs import Telemetry

        tele = Telemetry(
            ObsConfig(memory_sample_every=0),
            output_dir=str(tmp_path),
            slo_cfg=SloConfig(
                step_time_p99_s=1e-12, min_samples=1, check_every=1
            ),
        )
        calls: list[str] = []
        try:
            # Record trigger requests without opening real windows.
            tele.devprof.request = lambda reason: calls.append(reason) or True
            for s in range(1, 5):
                tele.on_step_start(s)
                tele.on_step_end(s, elapsed_s=0.0, synced=True)
        finally:
            tele.close()
        assert calls == ["slo_breach:step_time_p99_s"]

    def test_devprof_constructed_without_cadence_or_trigger(self, tmp_path):
        """On-demand capture stays available when both the cadence and
        the trigger knobs are off (the observatory is inert, not absent)."""
        from dtc_tpu.config.schema import ObsConfig
        from dtc_tpu.obs import Telemetry

        tele = Telemetry(
            ObsConfig(
                memory_sample_every=0, devprof_every=0,
                devprof_on_trigger=False,
            ),
            output_dir=str(tmp_path),
        )
        try:
            assert tele.devprof is not None
            assert tele.request_device_profile("manual") is True
            # ...but triggers are honored per the knob: hung_step must NOT
            # override the explicit opt-out (the manual request stays).
            tele.on_hung_step(step=1)
            assert tele.devprof._pending == "manual"
        finally:
            tele.close()

    def test_obs_config_validation(self):
        from dtc_tpu.config.schema import ObsConfig

        with pytest.raises(ValueError):
            ObsConfig(devprof_every=-1)
        with pytest.raises(ValueError):
            ObsConfig(devprof_steps=0)


# ---------------------------------------------------------------------------
# satellites


class TestSatellites:
    def test_hbm_watermark_shape(self):
        from dtc_tpu.obs.device import hbm_watermark

        w = hbm_watermark()
        assert set(w) == {"peak_hbm_bytes", "hbm_bytes_in_use"}
        # CPU backend: explicit nulls, never a crash
        assert w["peak_hbm_bytes"] is None or w["peak_hbm_bytes"] >= 0

    def test_utils_profiling_deprecation_warning(self):
        sys.modules.pop("dtc_tpu.utils.profiling", None)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            importlib.import_module("dtc_tpu.utils.profiling")
        assert any(
            issubclass(w.category, DeprecationWarning)
            and "dtc_tpu.obs.profiling" in str(w.message)
            for w in caught
        )

    def test_fixture_is_committed_not_generated(self):
        """Tests must not depend on live profiler output: the fixture's
        bytes are version-controlled and deterministic (gzip mtime=0)."""
        path = devprof.find_trace_file(FIXTURE)
        with open(path, "rb") as f:
            header = f.read(10)
        assert header[:2] == b"\x1f\x8b"          # gzip magic
        assert header[4:8] == b"\x00\x00\x00\x00"  # mtime pinned to 0
        with open(os.path.join(FIXTURE, "devprof_meta.json")) as f:
            assert json.load(f)["reason"] == "fixture"
