"""Fleet-router tests (ISSUE 13): tenant-aware routing over N engine
replicas with chaos-verified failover and zero silent drops.

The anchor invariant, lifted from test_serve.py to the fleet: the router
is a pure REORDERING of single-stream greedy decode — whatever dies
(replica kill, partition, stall), every COMPLETED request's tokens are
token-for-token ``generate()``'s, and every non-completed request
carries a typed error plus an obs event. Plus the engine-level satellite
contracts: graceful shutdown/drain, cross-replica resume accounting, and
the AdapterStore eviction/queued-request race.
"""

import json
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dtc_tpu.config.schema import (
    AdapterConfig,
    ChaosConfig,
    ModelConfig,
    RouterConfig,
    ServeConfig,
    StreamRetryConfig,
)
from dtc_tpu.generate import generate
from dtc_tpu.models.gpt import GPT
from dtc_tpu.obs import MemorySink, reduce_shards
from dtc_tpu.serve import (
    EngineClosedError,
    FleetRouter,
    FleetSaturatedError,
    QueueFullError,
    ReplicaState,
    Request,
    RequestFailedError,
    RequestState,
    ServingEngine,
    UnknownAdapterError,
)

VOCAB = 61


def _model_and_params(adapter_rank: int = 0):
    cfg = ModelConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=32, dropout=0.0, param_dtype="float32",
        compute_dtype="float32", attention="dense",
        adapter=AdapterConfig(rank=adapter_rank),
    )
    model = GPT(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.ones((1, 1), jnp.int32),
        train=False,
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def fleet_model():
    return _model_and_params()


@pytest.fixture(scope="module")
def lora_model():
    from dtc_tpu.adapters import init_lora

    model, params = _model_and_params(adapter_rank=4)
    factors = {
        "t1": init_lora(model, seed=1), "t2": init_lora(model, seed=2),
    }
    return model, params, factors


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=n).tolist() for n in sizes]


def _refs(model, params, prompts, n, lora=None):
    return [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None], n, lora=lora,
        ))[0].tolist()
        for p in prompts
    ]


def _rcfg(n_replicas=3, serve=None, **kw):
    kw.setdefault("retry", StreamRetryConfig(
        max_attempts=2, backoff_s=0.0, backoff_max_s=0.0, jitter=0.0))
    return RouterConfig(
        n_replicas=n_replicas,
        serve=serve or ServeConfig(
            slots=1, page_size=4, queue_depth=4, max_new_tokens=8,
            prefill_bucket=8,
        ),
        **kw,
    )


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_router_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(n_replicas=0)
    with pytest.raises(ValueError):
        RouterConfig(placement="coin_flip")
    with pytest.raises(ValueError):
        RouterConfig(heartbeat_miss_limit=0)
    # ISSUE 17: a chaos victim beyond the CONSTRUCTION-time fleet size is
    # legal config now — the replica set is dynamic (spawn/retire), so
    # the bound is judged when the fault fires (see
    # test_chaos_stale_target_is_typed_error_at_fire_time).
    RouterConfig(n_replicas=2, chaos=ChaosConfig(
        enabled=True, fleet_kill_replica_at_step=3, fleet_target_replica=5))
    RouterConfig(n_replicas=2, chaos=ChaosConfig(
        enabled=True, fleet_kill_replica_at_step=3, fleet_target_replica=1))


def test_chaos_stale_target_is_typed_error_at_fire_time(fleet_model):
    """Satellite (ISSUE 17): a fleet-chaos victim that does not exist at
    FIRE time raises a typed ChaosTargetError — never a silent no-op or
    a clamp onto some other replica — while a target only reachable via
    a later spawn fires correctly."""
    from dtc_tpu.resilience.errors import ChaosTargetError

    model, params = fleet_model
    # Stale target: replica 5 never exists in a 2-replica fleet.
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        chaos=ChaosConfig(enabled=True, fleet_kill_replica_at_step=1,
                          fleet_target_replica=5),
    ))
    router.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=4))
    with pytest.raises(ChaosTargetError, match="fleet_target_replica 5"):
        router.step()
    router.close()

    # The same victim id is LEGAL once a spawn has minted it: the drill
    # fires on the spawned replica (construction would have rejected it
    # under the old construction-time check).
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        chaos=ChaosConfig(enabled=True, fleet_kill_replica_at_step=1,
                          fleet_target_replica=2),
    ), router_proc=64)
    router.spawn_replica()
    router.submit(Request(rid="b", prompt=[1, 2, 3], max_new_tokens=4))
    router.run()
    assert router.replicas[2].state is ReplicaState.DEAD
    assert router.results["b"].state is RequestState.DONE
    router.close()


def test_fleet_chaos_config_validation():
    with pytest.raises(ValueError):
        ChaosConfig(fleet_partition_iters=0)
    with pytest.raises(ValueError):
        ChaosConfig(fleet_target_replica=-1)


def test_router_config_yaml_loads():
    """The committed configs/router_config.yaml round-trips through the
    loader with the committed model config."""
    from dtc_tpu.config.loader import load_router_config

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rcfg, mcfg = load_router_config(
        os.path.join(root, "configs", "router_config.yaml"),
        os.path.join(root, "configs", "model_config.yaml"),
    )
    assert rcfg.n_replicas == 3 and rcfg.placement == "affinity"
    assert rcfg.serve.slots == 4 and rcfg.watchdog.enabled
    assert mcfg.d_model > 0


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------

def test_tenant_affinity_routes_to_residency(lora_model):
    """Adapter residency IS cache affinity: the first tenant request
    lazy-loads the factors somewhere; every later one follows them (one
    load total), while base requests spread by least-loaded."""
    model, params, factors = lora_model
    router = FleetRouter(model, params, _rcfg(
        serve=ServeConfig(slots=2, page_size=4, queue_depth=8,
                          max_new_tokens=4, prefill_bucket=8,
                          max_adapters=4)))
    router.register_adapter("t1", factors["t1"])
    prompts = _prompts(0, (4, 5, 6, 4, 5, 6))
    homes = []
    for i in range(3):
        router.submit(Request(rid=f"a{i}", prompt=prompts[i],
                              max_new_tokens=4, adapter="t1"))
        homes.append(router.records[f"a{i}"].replica)
    assert len(set(homes)) == 1, f"tenant spread across {homes}"
    assert router.reg.counter("router_adapter_loads").value == 1
    base_homes = []
    for i in range(3, 6):
        router.submit(Request(rid=f"b{i}", prompt=prompts[i],
                              max_new_tokens=4))
        base_homes.append(router.records[f"b{i}"].replica)
    # Least-loaded spreads the base requests off the tenant's busy home.
    assert len(set(base_homes)) > 1
    res = router.run(max_steps=300)
    assert all(r.state is RequestState.DONE for r in res.values())


def test_prefix_affinity_routes_to_prefix_store(fleet_model):
    """A shared system prompt routes to the replica whose prefix store
    already holds its KV — even when that replica is more loaded."""
    model, params = fleet_model
    router = FleetRouter(model, params, _rcfg(
        serve=ServeConfig(slots=2, page_size=4, queue_depth=8,
                          max_new_tokens=4, prefill_bucket=8)))
    rng = np.random.RandomState(3)
    prefix = rng.randint(0, VOCAB, size=6).tolist()
    p1 = prefix + rng.randint(0, VOCAB, size=3).tolist()
    p2 = prefix + rng.randint(0, VOCAB, size=4).tolist()
    router.submit(Request(rid="p1", prompt=p1, max_new_tokens=4,
                          shared_prefix_len=len(prefix)))
    home = router.records["p1"].replica
    router.step()  # admission builds the prefix store entry on `home`
    router.submit(Request(rid="p2", prompt=p2, max_new_tokens=4,
                          shared_prefix_len=len(prefix)))
    assert router.records["p2"].replica == home
    res = router.run(max_steps=200)
    assert all(r.state is RequestState.DONE for r in res.values())
    # The prefix was built once, fleet-wide.
    builds = sum(
        rep.engine.reg.counter("serve_prefix_builds").value
        for rep in router.replicas
    )
    hits = sum(
        rep.engine.reg.counter("serve_prefix_hits").value
        for rep in router.replicas
    )
    assert builds == 1 and hits >= 1


def test_round_robin_placement(fleet_model):
    model, params = fleet_model
    router = FleetRouter(model, params, _rcfg(placement="round_robin"))
    prompts = _prompts(1, (4, 4, 4))
    reps = []
    for i, p in enumerate(prompts):
        router.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=2))
        reps.append(router.records[f"r{i}"].replica)
    assert len(set(reps)) == 3
    router.run(max_steps=200)


# ---------------------------------------------------------------------------
# fleet backpressure
# ---------------------------------------------------------------------------

def test_fleet_backpressure_is_typed_and_coordinated(fleet_model):
    """The router routes AROUND full replicas (coordinating, not
    overriding, per-replica admission); only when every live queue is
    full does submit raise — typed FleetSaturatedError (a
    QueueFullError), never a silent drop. Every accepted rid still
    reaches a terminal result."""
    model, params = fleet_model
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=2,
                          max_new_tokens=4, prefill_bucket=8,
                          shed_watermark=0.0)))
    prompts = _prompts(2, tuple([4] * 8))
    accepted, rejected = [], 0
    for i, p in enumerate(prompts):
        try:
            router.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=4))
            accepted.append(f"r{i}")
        except FleetSaturatedError as e:
            assert isinstance(e, QueueFullError)
            rejected += 1
    assert rejected > 0 and len(accepted) == 4  # 2 replicas x queue 2
    # Accepted work spread over BOTH replicas (routed around the full one).
    assert len({router.records[r].replica for r in accepted}) == 2
    assert router.reg.counter("router_rejected").value == rejected
    res = router.run(max_steps=300)
    assert sorted(res) == sorted(accepted)
    assert all(r.state is RequestState.DONE for r in res.values())


# ---------------------------------------------------------------------------
# health state machine
# ---------------------------------------------------------------------------

def test_stall_degrades_then_recovers(fleet_model):
    """An injected fleet stall (outside the engine's timed iteration —
    the replica-level watchdog's job) marks the victim DEGRADED: new
    placements avoid it while peers have room, and it recovers HEALTHY
    after the hold window."""
    model, params = fleet_model
    # Real clock: the replica watchdog judges real step durations (the
    # healthy median is milliseconds of tiny-model decode; the 1 s stall
    # is a ~100x outlier — far past the default 8x factor).
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2, degraded_hold_iters=3,
        serve=ServeConfig(slots=2, page_size=4, queue_depth=8,
                          max_new_tokens=24, prefill_bucket=8),
        # Step 12: past the replica watchdog's min_samples=8 default, so
        # the trailing median is armed when the stall lands.
        chaos=ChaosConfig(enabled=True, fleet_stall_replica_at_step=12,
                          fleet_target_replica=0, stall_s=1.0),
    ))
    # Keep the victim working so the watchdog has a healthy-median
    # baseline of real decode iterations before the stall lands.
    p = _prompts(4, (4,))[0]
    router.submit(Request(rid="warm", prompt=p, max_new_tokens=24))
    victim = router.replicas[0]
    sink = router.reg.add_sink(MemorySink())
    for _ in range(20):
        router.step()
        if victim.state is ReplicaState.DEGRADED:
            break
    assert victim.state is ReplicaState.DEGRADED
    assert victim.hung_flags >= 1
    # New work lands on the healthy peer while it has room.
    router.submit(Request(rid="after", prompt=p, max_new_tokens=4))
    assert router.records["after"].replica == 1
    # ...and the victim recovers after the hold window.
    for _ in range(40):
        router.step()
        if victim.state is ReplicaState.HEALTHY:
            break
    assert victim.state is ReplicaState.HEALTHY
    states = [e for e in sink.events if e["etype"] == "router_replica_state"]
    assert [e["state"] for e in states][:2] == ["degraded", "healthy"]


def test_partition_short_heals_in_place(fleet_model):
    """A partition shorter than the heartbeat-miss budget: missed beats
    counted, nobody dies, nothing fails over, everything completes."""
    model, params = fleet_model
    prompts = _prompts(5, (4, 5))
    refs = _refs(model, params, prompts, 8)
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2, heartbeat_miss_limit=3,
        chaos=ChaosConfig(enabled=True, fleet_partition_at_step=2,
                          fleet_partition_iters=2, fleet_target_replica=0),
    ))
    for i, p in enumerate(prompts):
        router.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=8))
    res = router.run(max_steps=300)
    assert router.reg.counter("router_missed_heartbeats").value == 2
    assert router.reg.counter("router_replica_deaths").value == 0
    assert router.replicas[0].state is ReplicaState.HEALTHY
    for i in range(len(prompts)):
        assert res[f"r{i}"].state is RequestState.DONE
        assert res[f"r{i}"].tokens == refs[i]
        assert res[f"r{i}"].n_hops == 0


def test_partition_sustained_escalates_to_failover(fleet_model):
    """A partition outliving the miss budget: the replica is declared
    dead and its requests fail over — completed token-identical on the
    survivor."""
    model, params = fleet_model
    prompts = _prompts(6, (4, 5, 6, 4))
    refs = _refs(model, params, prompts, 8)
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2, heartbeat_miss_limit=2,
        serve=ServeConfig(slots=2, page_size=4, queue_depth=8,
                          max_new_tokens=8, prefill_bucket=8),
        chaos=ChaosConfig(enabled=True, fleet_partition_at_step=3,
                          fleet_partition_iters=50, fleet_target_replica=0),
    ))
    for i, p in enumerate(prompts):
        router.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=8))
    res = router.run(max_steps=400)
    assert router.replicas[0].state is ReplicaState.DEAD
    assert "heartbeat" in (router.replicas[0].dead_reason or "")
    assert router.reg.counter("router_failovers").value >= 1
    for i in range(len(prompts)):
        assert res[f"r{i}"].state is RequestState.DONE, res[f"r{i}"].error
        assert res[f"r{i}"].tokens == refs[i]


# ---------------------------------------------------------------------------
# failover accounting (satellite: requeue timing across hops)
# ---------------------------------------------------------------------------

def test_multi_hop_failover_restarts_queued_span_and_keeps_ttft(fleet_model):
    """The requeue-timing fix, regression-tested over a multi-hop chain:
    each hop restarts the ``req.queued`` span (span durations measure
    THIS hop's wait, not submit-to-now), while ``submitted_t`` — and so
    TTFT — stays anchored at the ORIGINAL submit, so fleet TTFT
    histograms include the full failover cost."""
    model, params = fleet_model
    clock = FakeClock()
    router = FleetRouter(model, params, _rcfg(
        n_replicas=3,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=4,
                          max_new_tokens=10, prefill_bucket=8),
    ), clock=clock, sleep=clock.advance)
    sinks = [rep.engine.reg.add_sink(MemorySink()) for rep in router.replicas]
    p = _prompts(7, (5,))[0]
    ref = _refs(model, params, [p], 10)[0]

    router.submit(Request(rid="r0", prompt=p, max_new_tokens=10))
    first = router.records["r0"].replica
    clock.advance(100.0)          # 100 fake seconds queued on hop 0
    router.kill_replica(first, reason="test")   # hop 1: still queued
    assert router.records["r0"].hops == 1
    second = router.records["r0"].replica
    for _ in range(3):            # admit + a few tokens on the survivor
        clock.advance(0.01)
        router.step()
    assert len(router.records["r0"].tokens) >= 1
    clock.advance(5.0)
    router.kill_replica(second, reason="test")  # hop 2: mid-decode
    res = router.run(max_steps=200)["r0"]

    assert res.state is RequestState.DONE
    assert res.tokens == ref      # token-identical across two failovers
    assert res.n_hops == 2
    # TTFT anchored at the ORIGINAL submit: it must include the 100 s
    # spent before the first failover (the under-reporting this fixes).
    assert res.submitted_t == 0.0
    assert res.ttft_s is not None and res.ttft_s >= 100.0
    # Each admitted hop emitted its own restarted req.queued span whose
    # duration covers THIS hop's wait only (< the 100 s original wait).
    spans = [
        e for s in sinks for e in s.events
        if e["etype"] == "span" and e.get("name") == "req.queued"
        and e.get("rid") == "r0"
    ]
    assert len(spans) == 2        # one per admitted hop (hop 0 never admitted)
    assert all(e["dur_s"] < 100.0 for e in spans)


def test_failover_budget_exhaustion_is_typed(fleet_model):
    """Past failover_max_hops the request ends typed (RequestFailedError)
    — bounded ping-pong, zero silent drops."""
    model, params = fleet_model
    router = FleetRouter(model, params, _rcfg(
        n_replicas=3, failover_max_hops=1,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=4,
                          max_new_tokens=16, prefill_bucket=8),
    ))
    p = _prompts(8, (5,))[0]
    router.submit(Request(rid="r0", prompt=p, max_new_tokens=16))
    router.kill_replica(router.records["r0"].replica, reason="test")
    assert router.records["r0"].hops == 1
    router.step()
    router.kill_replica(router.records["r0"].replica, reason="test")
    res = router.results["r0"]
    assert res.state is RequestState.FAILED
    assert isinstance(res.error, RequestFailedError)
    assert "failover budget" in str(res.error)


# ---------------------------------------------------------------------------
# tenants under failover (satellite: AdapterStore race)
# ---------------------------------------------------------------------------

def test_tenant_failover_reloads_factors_on_survivor(lora_model):
    """Killing a tenant's home replica re-routes its requests to a
    survivor WITHOUT the factors resident: the router re-loads them from
    its registry and the output stays token-identical to generate() with
    the adapter — never a silent slot-0 base-weight decode."""
    model, params, factors = lora_model
    refs_prompt = _prompts(9, (5,))[0]
    ref = _refs(model, params, [refs_prompt], 8, lora=factors["t1"])[0]
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=4,
                          max_new_tokens=8, prefill_bucket=8,
                          max_adapters=4)))
    router.register_adapter("t1", factors["t1"])
    router.submit(Request(rid="r0", prompt=refs_prompt, max_new_tokens=8,
                          adapter="t1"))
    home = router.records["r0"].replica
    router.step()
    router.kill_replica(home, reason="test")
    res = router.run(max_steps=200)["r0"]
    assert res.state is RequestState.DONE
    assert res.n_hops == 1
    assert res.tokens == ref
    survivor = router.replicas[1 - home]
    assert "t1" in survivor.resident_adapters()
    assert router.reg.counter("router_adapter_loads").value == 2


def test_unregistered_tenant_failover_fails_typed_never_base(lora_model):
    """The UnknownAdapterError path: factors loaded engine-direct on one
    replica only (NOT registered with the router). When that replica
    dies, no survivor can serve the tenant — the request must end typed
    with UnknownAdapterError as the cause, not complete on base weights."""
    model, params, factors = lora_model
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=4,
                          max_new_tokens=8, prefill_bucket=8,
                          max_adapters=4)))
    sink = router.reg.add_sink(MemorySink())
    router.replicas[0].engine.load_adapter("t2", factors["t2"])
    p = _prompts(10, (5,))[0]
    router.submit(Request(rid="r0", prompt=p, max_new_tokens=8, adapter="t2"))
    assert router.records["r0"].replica == 0  # affinity found the residency
    router.step()
    router.kill_replica(0, reason="test")
    res = router.results["r0"]
    assert res.state is RequestState.FAILED
    assert isinstance(res.error, RequestFailedError)
    assert isinstance(res.error.__cause__, UnknownAdapterError)
    # Typed terminal event in the stream — the no-silent-drop backstop.
    terminal = [e for e in sink.events if e["etype"] == "serve_request"]
    assert [e["rid"] for e in terminal] == ["r0"]
    assert terminal[0]["error"] == "RequestFailedError"


def test_adapter_store_eviction_cannot_race_queued_request(lora_model):
    """Engine-level satellite: a tenant with a request sitting in the
    queue is refcount-pinned — loading more tenants into a full store
    raises typed AdapterStoreFullError instead of evicting it, and the
    queued request decodes under ITS factors (token-identical). After
    the tenant drains, eviction may proceed; a new request for the
    evicted tenant is typed-rejected, never served on base weights."""
    from dtc_tpu.serve import AdapterStoreFullError

    model, params, factors = lora_model
    prompts = _prompts(11, (5, 4))
    ref = _refs(model, params, [prompts[0]], 6, lora=factors["t1"])[0]
    eng = ServingEngine(model, params, ServeConfig(
        slots=1, page_size=4, queue_depth=4, max_new_tokens=6,
        prefill_bucket=8, max_adapters=2,  # exactly ONE tenant slot
    ))
    eng.load_adapter("t1", factors["t1"])
    eng.submit(Request(rid="q", prompt=prompts[0], max_new_tokens=6,
                       adapter="t1"))
    # Queued (not yet admitted): the refcount pin must block eviction.
    with pytest.raises(AdapterStoreFullError):
        eng.load_adapter("t2", factors["t2"])
    res = eng.run(max_steps=100)
    assert res["q"].state is RequestState.DONE
    assert res["q"].tokens == ref  # decoded under t1, not base
    # Drained: now the LRU eviction is legal...
    eng.load_adapter("t2", factors["t2"])
    # ...and the evicted tenant is typed-unknown, never silently base.
    with pytest.raises(UnknownAdapterError):
        eng.submit(Request(rid="q2", prompt=prompts[1], max_new_tokens=6,
                           adapter="t1"))


# ---------------------------------------------------------------------------
# graceful shutdown / drain (satellite)
# ---------------------------------------------------------------------------

def test_engine_shutdown_drain_finishes_and_refuses(fleet_model):
    """ServingEngine.shutdown(mode="drain"): in-flight requests finish
    (token-identical), later submits raise typed EngineClosedError, the
    bus is drained and the flight recorder dumped once."""
    model, params = fleet_model
    prompts = _prompts(12, (5, 6))
    refs = _refs(model, params, prompts, 6)
    eng = ServingEngine(model, params, ServeConfig(
        slots=2, page_size=4, queue_depth=4, max_new_tokens=6,
        prefill_bucket=8))
    sink = eng.reg.add_sink(MemorySink())
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=6))
    res = eng.shutdown(mode="drain")
    for i in range(len(prompts)):
        assert res[f"r{i}"].state is RequestState.DONE
        assert res[f"r{i}"].tokens == refs[i]
    with pytest.raises(EngineClosedError):
        eng.submit(Request(rid="late", prompt=[1, 2], max_new_tokens=2))
    assert any(e["etype"] == "serve_shutdown" for e in sink.events)
    assert len(eng.recorder.events) > 0  # ring captured the run
    # Idempotent.
    assert eng.shutdown() is res or eng.shutdown() == res


def test_engine_shutdown_evict_is_typed_with_partial_tokens(fleet_model):
    """mode="evict" (hard preemption): queued AND mid-decode requests end
    FAILED + EngineClosedError with partial tokens preserved — typed,
    zero silent drops, one serve_request event each."""
    model, params = fleet_model
    prompts = _prompts(13, (5, 6, 4))
    eng = ServingEngine(model, params, ServeConfig(
        slots=1, page_size=4, queue_depth=4, max_new_tokens=12,
        prefill_bucket=8))
    sink = eng.reg.add_sink(MemorySink())
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=12))
    for _ in range(4):
        eng.step()  # r0 mid-decode, r1/r2 queued
    res = eng.shutdown(mode="evict", reason="preemption notice")
    states = {rid: r.state for rid, r in res.items()}
    assert all(s is RequestState.FAILED for s in states.values())
    assert all(isinstance(r.error, EngineClosedError) for r in res.values())
    assert len(res["r0"].tokens) >= 1  # partial progress preserved
    terminal = [e for e in sink.events if e["etype"] == "serve_request"]
    assert sorted(e["rid"] for e in terminal) == sorted(res)


def test_router_drain_on_sigterm(fleet_model):
    """SIGTERM = fleet drain: the handler flags, run() drains every
    replica through the engine shutdown contract, every accepted request
    terminal, every replica retired DEAD("drained")."""
    model, params = fleet_model
    prompts = _prompts(14, (5, 6, 4))
    refs = _refs(model, params, prompts, 6)
    router = FleetRouter(model, params, _rcfg(
        n_replicas=2,
        serve=ServeConfig(slots=1, page_size=4, queue_depth=4,
                          max_new_tokens=6, prefill_bucket=8)))
    router.install_sigterm()
    try:
        for i, p in enumerate(prompts):
            router.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=6))
        os.kill(os.getpid(), signal.SIGTERM)
        res = router.run(max_steps=300)
    finally:
        router.restore_sigterm()
    for i in range(len(prompts)):
        assert res[f"r{i}"].state is RequestState.DONE
        assert res[f"r{i}"].tokens == refs[i]
    assert all(r.state is ReplicaState.DEAD for r in router.replicas)
    assert all(r.dead_reason == "drained" for r in router.replicas)
    assert all(r.engine.closed for r in router.replicas)


# ---------------------------------------------------------------------------
# THE fleet chaos acceptance test (ISSUE 13 acceptance criterion)
# ---------------------------------------------------------------------------

def test_fleet_chaos_acceptance_kill_mid_decode(fleet_model, tmp_path):
    """Seeded Poisson traffic on a 3-replica fleet; chaos kills one
    replica mid-decode. (a) every completed request token-identical to
    the clean single-stream reference; (b) every non-completed request
    terminal with a typed ServeResult + obs event — zero silent drops,
    verified by reconciling submitted rids against drained results;
    (c) the mixed-fleet reducer over the per-replica shards shows the
    fleet AND per-replica p99 rows, failover hops included."""
    model, params = fleet_model
    obs_dir = str(tmp_path / "obs")
    n_req = 10
    rng = np.random.RandomState(21)
    arrivals = np.cumsum(rng.exponential(0.02, size=n_req))
    prompts = [rng.randint(0, VOCAB, size=4 + i % 4).tolist()
               for i in range(n_req)]
    refs = _refs(model, params, prompts, 8)

    router = FleetRouter(model, params, _rcfg(
        n_replicas=3,
        serve=ServeConfig(slots=2, page_size=4, queue_depth=16,
                          max_new_tokens=8, prefill_bucket=8),
        chaos=ChaosConfig(enabled=True, fleet_kill_replica_at_step=4,
                          fleet_target_replica=0),
    ), obs_dir=obs_dir)
    sinks = [rep.engine.reg.add_sink(MemorySink())
             for rep in router.replicas]
    sinks.append(router.reg.add_sink(MemorySink()))

    import time as _time

    submitted = []
    i = 0
    t0 = _time.perf_counter()
    for _ in range(500):
        now = _time.perf_counter() - t0
        while i < n_req and arrivals[i] <= now:
            router.submit(Request(rid=f"r{i}", prompt=prompts[i],
                                  max_new_tokens=8))
            submitted.append(f"r{i}")
            i += 1
        busy = router.step()
        if i >= n_req and not busy:
            break
    res = router.results
    router.close()

    # The kill fired mid-traffic and work failed over.
    assert router.replicas[0].state is ReplicaState.DEAD
    summ = router.fleet_summary()
    assert summ["replica_deaths"] == 1
    assert summ["failovers"] >= 1
    hopped = [r for r in res.values() if r.n_hops > 0]
    assert hopped, "kill exercised no failover"

    # (b) zero silent drops: submitted == terminal, all typed.
    assert sorted(res) == sorted(submitted)
    for r in res.values():
        assert r.state in (
            RequestState.DONE, RequestState.SHED, RequestState.EXPIRED,
            RequestState.FAILED,
        )
        assert (r.error is None) == (r.state is RequestState.DONE)
    events = [e for s in sinks for e in s.events
              if e["etype"] == "serve_request"]
    assert sorted({e["rid"] for e in events}) == sorted(submitted)

    # (a) token identity vs the clean single-stream reference for every
    # completed request — INCLUDING the failover hops.
    for i, rid in enumerate(submitted):
        if res[rid].state is RequestState.DONE:
            assert res[rid].tokens == refs[i], rid
    assert any(r.n_hops > 0 and r.state is RequestState.DONE
               for r in res.values())

    # (c) fleet metrics reduced across the per-replica shards: per-host
    # p99 rows + pooled fleet percentiles + the failover evidence.
    red = reduce_shards(obs_dir)
    assert red is not None and red["serve"]["requests"] >= n_req
    assert red["serve"].get("ttft_p99_s") is not None
    assert red["serve"].get("failover_hops", 0) >= 1
    per_replica = [h for k, h in red["hosts"].items()
                   if int(k) < 3 and h.get("serve_requests")]
    assert len(per_replica) >= 2  # survivors + the dead replica's record
    assert any(h.get("ttft_p99_s") is not None for h in per_replica)


# ---------------------------------------------------------------------------
# reducer + drift-guard satellites
# ---------------------------------------------------------------------------

def test_reducer_fleet_percentiles(tmp_path):
    """The mixed-fleet reducer derives per-host AND pooled fleet p50/p99
    from serve_request terminals (plus tokens/s and failover hops)."""
    from dtc_tpu.obs import shard_path

    def write(proc, events):
        with open(shard_path(str(tmp_path), proc), "w") as f:
            for e in events:
                f.write(json.dumps({"proc": proc, **e}) + "\n")

    write(0, [
        {"etype": "serve_request", "state": "done", "iteration": 5,
         "ts": 1.0, "ttft_s": 0.1, "ms_per_token": 10.0, "n_tokens": 8,
         "n_hops": 0},
        {"etype": "serve_request", "state": "done", "iteration": 9,
         "ts": 3.0, "ttft_s": 0.3, "ms_per_token": 30.0, "n_tokens": 8,
         "n_hops": 1},
    ])
    write(1, [
        {"etype": "serve_request", "state": "shed", "iteration": 7,
         "ts": 2.0, "ttft_s": 0.2, "n_tokens": 0, "n_hops": 0},
    ])
    red = reduce_shards(str(tmp_path))
    assert red["serve"]["requests"] == 3
    # Pooled percentiles come from merged histograms (ISSUE 16): exact
    # to within one log-bucket (growth 1.1), so assert rel=0.1 — the
    # per-host values below stay exact nearest-rank.
    assert red["serve"]["ttft_p50_s"] == pytest.approx(0.2, rel=0.1)
    assert red["serve"]["ttft_p99_s"] == pytest.approx(0.3, rel=0.1)
    assert red["serve"]["ms_per_token_p99"] == pytest.approx(30.0, rel=0.1)
    assert red["serve"]["failover_hops"] == 1
    assert red["serve"]["tokens_per_sec"] == 8.0  # 16 tokens / 2 s span
    assert red["hosts"]["0"]["ttft_p99_s"] == 0.3
    assert red["hosts"]["0"]["failover_hops"] == 1
    assert "ms_per_token_p99" not in red["hosts"]["1"]  # no samples


def test_drift_guard_fleet_rows_require_matching_replicas(tmp_path):
    """serve_fleet_* rows ride the serve drift family with the replica-
    count (and kill-leg) same-config rule: a 3-replica row is never
    judged against a 2-replica one."""
    from bench import decode_drift_guard

    d = str(tmp_path)
    base = {"platform": "cpu", "serve_model": "tiny",
            "kill_replica_at": 0}
    detail = {"serve_fleet_load90": {
        "ms_per_token": 10.0, "n_replicas": 3, **base}}
    with open(os.path.join(d, "BENCH_r01.json"), "w") as f:
        json.dump({"n": 1, "rc": 0,
                   "tail": "# bench-detail: " + json.dumps(detail)}, f)
    # Same replica count, +100%: flagged.
    extra = {"serve_fleet_load90": {
        "ms_per_token": 20.0, "n_replicas": 3, **base}}
    flags = decode_drift_guard(extra, d)
    assert len(flags) == 1 and "serve_fleet_load90" in flags[0]
    # Different replica count: not comparable.
    extra = {"serve_fleet_load90": {
        "ms_per_token": 20.0, "n_replicas": 2, **base}}
    assert decode_drift_guard(extra, d) == []
    # Kill leg vs clean leg: not comparable either.
    extra = {"serve_fleet_load90": {
        "ms_per_token": 20.0, "n_replicas": 3, "platform": "cpu",
        "serve_model": "tiny", "kill_replica_at": 8}}
    assert decode_drift_guard(extra, d) == []


def test_resume_submit_engine_level(fleet_model):
    """The engine's cross-replica resume primitive in isolation: partial
    progress on engine A resumes on engine B token-identically, with
    submitted_t preserved and the hop counted."""
    model, params = fleet_model
    p = _prompts(15, (5,))[0]
    ref = _refs(model, params, [p], 8)[0]
    scfg = ServeConfig(slots=1, page_size=4, queue_depth=4,
                       max_new_tokens=8, prefill_bucket=8)
    a = ServingEngine(model, params, scfg)
    a.submit(Request(rid="r", prompt=p, max_new_tokens=8))
    for _ in range(4):
        a.step()
    partial = a.results["r"]
    assert partial.state is RequestState.DECODE
    assert 0 < len(partial.tokens) < 8

    b = ServingEngine(model, params, scfg)
    b.submit(Request(rid="r", prompt=p, max_new_tokens=8), resume=partial)
    res = b.run(max_steps=100)["r"]
    assert res.state is RequestState.DONE
    assert res.tokens == ref
    assert res.n_hops == 1
    assert res.submitted_t == partial.submitted_t
    # A resume that should already be complete is a caller bug.
    done = b.results if "r" in b.results else {}
    with pytest.raises(ValueError, match="resume"):
        b.drain_results()
        b.submit(Request(rid="r2", prompt=p, max_new_tokens=2),
                 resume=res)
