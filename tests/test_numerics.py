"""Numerics + memory auditor tests (ISSUE 14).

Same two-layer structure as tests/test_analysis.py: the rule families on
FABRICATED evidence (every rule demonstrated non-vacuous — including the
acceptance-criteria case of ``bf16_mixed`` declared over an all-fp32
lowering), the parsers on hand-written StableHLO text, and a slow
green-path leg lowering the real ``bf16`` registry entry against its
committed baselines.
"""

import dataclasses
import os

import pytest

from dtc_tpu.analysis import dtypelint, memory, numerics
from dtc_tpu.analysis.lowering import Artifact
from dtc_tpu.analysis.rules import (
    audit_dtype_literals,
    audit_memory,
    audit_numerics,
)

# --------------------------------------------------------------------------
# fabricated StableHLO snippets
# --------------------------------------------------------------------------

#: healthy bf16 program: bf16 dot, f32-accumulating score dot (bf16
#: operands, f32 result), its autodiff transpose (one upcast operand),
#: fp32 softmax exp + LN rsqrt.
_SH_BF16 = """\
module @jit_step {
  func.func public @main(%arg0: tensor<8x64xbf16>, %arg1: tensor<64x64xbf16>) -> tensor<8x64xf32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<8x64xbf16>, tensor<64x64xbf16>) -> tensor<8x64xbf16>
    %1 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<8x64xbf16>, tensor<64x64xbf16>) -> tensor<8x64xf32>
    %2 = stablehlo.convert %arg1 : (tensor<64x64xbf16>) -> tensor<64x64xf32>
    %3 = stablehlo.dot_general %1, %2, contracting_dims = [1] x [0] : (tensor<8x64xf32>, tensor<64x64xf32>) -> tensor<8x64xf32>
    %4 = stablehlo.exponential %3 : tensor<8x64xf32>
    %5 = stablehlo.rsqrt %3 : tensor<8x64xf32>
    return %4 : tensor<8x64xf32>
  }
}
"""

#: the cast-then-dot LEAK: both operands upcast bf16->f32 then dotted.
_SH_UPCAST = """\
module @jit_step {
  func.func public @main(%arg0: tensor<8x64xbf16>, %arg1: tensor<64x64xbf16>) -> tensor<8x64xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<8x64xbf16>) -> tensor<8x64xf32>
    %1 = stablehlo.convert %arg1 : (tensor<64x64xbf16>) -> tensor<64x64xf32>
    %2 = stablehlo.dot_general %0, %1, contracting_dims = [1] x [0] : (tensor<8x64xf32>, tensor<64x64xf32>) -> tensor<8x64xf32>
    return %2 : tensor<8x64xf32>
  }
}
"""

#: bf16-downcast softmax/LN: the dangerous-downcast case.
_SH_BF16_EXP = """\
module @jit_step {
  func.func public @main(%arg0: tensor<8x64xbf16>) -> tensor<8x64xbf16> {
    %0 = stablehlo.exponential %arg0 : tensor<8x64xbf16>
    %1 = stablehlo.rsqrt %arg0 : tensor<8x64xbf16>
    %2 = stablehlo.dot_general %0, %1, contracting_dims = [1] x [0] : (tensor<8x64xbf16>, tensor<8x64xbf16>) -> tensor<8x8xbf16>
    return %0 : tensor<8x64xbf16>
  }
}
"""

#: layer scan with OUTLINED body (the real jax shape): the while body
#: slices the stacked f32 params, calls @None, and @None downcasts its
#: param arg per layer — the cast-churn fingerprint. One extra convert
#: of an ACTIVATION arg rides along and must NOT be counted.
_SH_SCAN_CHURN = """\
module @jit_step {
  func.func public @main(%arg0: tensor<4x64x64xf32>, %arg1: tensor<8x64xbf16>) -> tensor<8x64xbf16> {
    %c0 = stablehlo.constant dense<0> : tensor<i32>
    %51:2 = stablehlo.while(%iterArg = %arg0, %iterArg_1 = %arg1) : tensor<4x64x64xf32>, tensor<8x64xbf16>
     cond {
      %90 = stablehlo.compare LT, %c0, %c0 : (tensor<i32>, tensor<i32>) -> tensor<i1>
      stablehlo.return %90 : tensor<i1>
    } do {
      %100 = stablehlo.dynamic_slice %iterArg, %c0, %c0, %c0, sizes = [1, 64, 64] : (tensor<4x64x64xf32>, tensor<i32>, tensor<i32>, tensor<i32>) -> tensor<1x64x64xf32>
      %101 = stablehlo.reshape %100 : (tensor<1x64x64xf32>) -> tensor<64x64xf32>
      %102 = func.call @None(%101, %iterArg_1) : (tensor<64x64xf32>, tensor<8x64xbf16>) -> tensor<8x64xbf16>
      stablehlo.return %iterArg, %102 : tensor<4x64x64xf32>, tensor<8x64xbf16>
    }
    return %51#1 : tensor<8x64xbf16>
  }
  func.func private @None(%arg0: tensor<64x64xf32>, %arg1: tensor<8x64xbf16>) -> tensor<8x64xbf16> {
    %0 = stablehlo.convert %arg0 : (tensor<64x64xf32>) -> tensor<64x64xbf16>
    %1 = stablehlo.convert %arg1 : (tensor<8x64xbf16>) -> tensor<8x64xf32>
    %2 = stablehlo.convert %1 : (tensor<8x64xf32>) -> tensor<8x64xbf16>
    %3 = stablehlo.dot_general %2, %0, contracting_dims = [1] x [0] : (tensor<8x64xbf16>, tensor<64x64xbf16>) -> tensor<8x64xbf16>
    return %3 : tensor<8x64xbf16>
  }
}
"""

#: all-fp32 program (what "told bf16_mixed over today's lowering" sees).
_SH_FP32 = """\
module @jit_step {
  func.func public @main(%arg0: tensor<8x64xf32>, %arg1: tensor<64x64xf32>) -> tensor<8x64xf32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<8x64xf32>, tensor<64x64xf32>) -> tensor<8x64xf32>
    %1 = stablehlo.exponential %0 : tensor<8x64xf32>
    %2 = stablehlo.rsqrt %0 : tensor<8x64xf32>
    return %1 : tensor<8x64xf32>
  }
}
"""

_HLO_HEADER = (
    "HloModule jit_step, is_scheduled=true, "
    "input_output_alias={ {0}: (0, {}, may-alias) }, "
    "entry_computation_layout={(f32[64,64]{1,0}, s32[8,32]{1,0}, "
    "s32[8,32]{1,0}, u32[2]{0})->(f32[64,64]{1,0}, f32[])}\n"
)
_HLO_BODY = "  %all-reduce.1 = f32[64,64]{1,0} all-reduce(%p0)\n"


def _artifact(**over) -> Artifact:
    base = dict(
        name="train_dp",
        kind="train",
        parallel="dp",
        mesh_shape={"pipe": 1, "data": 8, "model": 1},
        batch=8,
        seq_len=32,
        hlo_text=_HLO_HEADER + _HLO_BODY,
        stablehlo_text=_SH_FP32,
        expected_donated=1,
        param_shapes=[],
        weak_outputs=0,
        n_layers=4,
        moe_experts=0,
        compute_dtype="float32",
        cold_compiles=1,
        steady_compiles=0,
        comm_estimate=None,
        precision="fp32",
        loss_dtype="f32",
        state_bytes={"params": 16384, "opt_moments": 0, "opt_other": 0},
        state_dtypes={"params": ["f32"], "opt_moments": ["f32"]},
        batch_bytes=2 * 8 * 32 * 4 + 8,
        mem_stats=None,
        mem_estimate=None,
    )
    base.update(over)
    return Artifact(**base)


def _errors(findings, rule_prefix=""):
    return [
        f for f in findings
        if f.severity == "error" and f.rule.startswith(rule_prefix)
    ]


# --------------------------------------------------------------------------
# numerics.py parsers on fabricated text
# --------------------------------------------------------------------------

def test_dot_signature_census_classifies():
    dots = numerics.dot_signature_census(_SH_BF16)
    # bf16xbf16->bf16 and bf16xbf16->f32 (f32 ACCUMULATION) both count as
    # the bf16 region; the transpose dot (f32 cotangent x upcast primal)
    # is its own benign bucket.
    assert dots == {
        "bf16_bf16": 2, "bf16_mixed": 0, "f32_f32": 0,
        "f32_transpose": 1, "f32_upcast": 0, "other": 0,
    }


def test_dot_census_flags_double_upcast_leak():
    dots = numerics.dot_signature_census(_SH_UPCAST)
    assert dots["f32_upcast"] == 1
    assert dots["f32_transpose"] == 0


def test_dot_census_ignores_algorithm_attr_types():
    # The algorithm attribute names dtypes inside <...>; the signature
    # split must read the REAL operand types after the last " : ".
    txt = (
        "module @m {\n"
        "  func.func public @main(%arg0: tensor<8x8xbf16>) -> tensor<8x8xf32> {\n"
        "    %0 = stablehlo.dot_general %arg0, %arg0, contracting_dims = [1] x [0],"
        " algorithm = <lhs_precision_type = bf16, rhs_precision_type = bf16,"
        " accumulation_type = f32> : (tensor<8x8xbf16>, tensor<8x8xbf16>) -> tensor<8x8xf32>\n"
        "    return %0 : tensor<8x8xf32>\n"
        "  }\n"
        "}\n"
    )
    assert numerics.dot_signature_census(txt)["bf16_bf16"] == 1


def test_fp32_region_census():
    assert numerics.fp32_region_census(_SH_BF16) == {
        "exponential": {"f32": 1}, "rsqrt": {"f32": 1},
    }
    low = numerics.fp32_region_census(_SH_BF16_EXP)
    assert low["exponential"] == {"bf16": 1}
    assert low["rsqrt"] == {"bf16": 1}


def test_scan_convert_census_outlined_body():
    c = numerics.scan_convert_census(_SH_SCAN_CHURN)
    # @None is called from the while body: its param-arg downcast counts
    # (the call site feeds a slice-of-carry), the activation round-trip
    # does not (its root arg position is fed by the carry directly).
    assert c["param_slice_downcast"] == 1
    assert c["f32_to_bf16"] == 2  # param cast + activation round-trip
    assert c["bf16_to_f32"] == 1


def test_scan_convert_census_ignores_top_level():
    # The same converts OUTSIDE any while body are not churn.
    assert numerics.scan_convert_census(_SH_UPCAST) == {
        "f32_to_bf16": 0, "bf16_to_f32": 0, "param_slice_downcast": 0,
    }


# --------------------------------------------------------------------------
# family 6: numerics rules
# --------------------------------------------------------------------------

def test_bf16_mixed_over_fp32_program_trips():
    """THE acceptance-criteria case: the auditor must trip when told
    bf16_mixed over today's all-fp32 lowering — zero bf16 matmuls and no
    master weights is not a lowered policy, whatever the config says."""
    a = _artifact(
        precision="bf16_mixed",
        state_dtypes={"params": ["f32"], "opt_moments": ["f32"]},
    )
    found = audit_numerics(a)
    assert _errors(found, "numerics.matmul_region")
    assert _errors(found, "numerics.optimizer_state")  # no f32 masters


def test_healthy_bf16_mixed_is_clean():
    a = _artifact(
        precision="bf16_mixed",
        stablehlo_text=_SH_BF16,
        state_dtypes={
            "params": ["bf16", "f32"], "opt_moments": ["f32"],
            "opt_master": ["f32"],
        },
    )
    assert audit_numerics(a) == []


def test_upcast_leak_trips():
    a = _artifact(stablehlo_text=_SH_BF16 + _SH_UPCAST)
    assert _errors(audit_numerics(a), "numerics.upcast_leak")


def test_bf16_softmax_ln_trips_under_any_policy():
    a = _artifact(stablehlo_text=_SH_BF16_EXP)
    found = _errors(audit_numerics(a), "numerics.fp32_mandatory")
    assert len(found) == 2  # exponential AND rsqrt


def test_cast_churn_warns_fp32_errors_bf16():
    a = _artifact(stablehlo_text=_SH_SCAN_CHURN)
    warns = [f for f in audit_numerics(a) if f.rule == "numerics.cast_churn"]
    assert warns and warns[0].severity == "warn"
    a_bf16 = _artifact(
        stablehlo_text=_SH_SCAN_CHURN,
        precision="bf16_mixed",
        state_dtypes={
            "params": ["bf16", "f32"], "opt_moments": ["f32"],
            "opt_master": ["f32"],
        },
    )
    assert _errors(audit_numerics(a_bf16), "numerics.cast_churn")


def test_loss_dtype_and_moment_dtype_trip():
    assert _errors(
        audit_numerics(_artifact(loss_dtype="bf16")), "numerics.loss_dtype"
    )
    assert _errors(
        audit_numerics(_artifact(
            state_dtypes={"params": ["f32"], "opt_moments": ["bf16"]},
        )),
        "numerics.optimizer_state",
    )


def test_bf16_collective_under_fp32_policy_trips():
    body = "  %all-reduce.9 = bf16[64,64]{1,0} all-reduce(%g)\n"
    a = _artifact(hlo_text=_HLO_HEADER + _HLO_BODY + body)
    assert _errors(audit_numerics(a), "numerics.grad_accum_downcast")
    # Under bf16_mixed the bf16 wire is the documented choice: no error.
    a2 = _artifact(
        hlo_text=_HLO_HEADER + _HLO_BODY + body,
        stablehlo_text=_SH_BF16,
        precision="bf16_mixed",
        state_dtypes={
            "params": ["bf16", "f32"], "opt_moments": ["f32"],
            "opt_master": ["f32"],
        },
    )
    assert not _errors(audit_numerics(a2), "numerics.grad_accum_downcast")


# --------------------------------------------------------------------------
# family 7: static memory plan
# --------------------------------------------------------------------------

def test_entry_io_bytes_parse():
    assert memory.entry_input_bytes(_HLO_HEADER) == (
        64 * 64 * 4 + 2 * 8 * 32 * 4 + 2 * 4
    )
    assert memory.entry_output_bytes(_HLO_HEADER) == 64 * 64 * 4 + 4


def test_hbm_plan_hand_computed():
    a = _artifact(
        state_bytes={"params": 16384, "opt_moments": 32768, "opt_other": 8},
        batch_bytes=2048,
        mem_stats={"argument": 0, "output": 0, "temp": 4096, "alias": 0},
    )
    plan = memory.hbm_plan(a)
    assert plan["params"] == 16384
    assert plan["comm_buffers"] == 64 * 64 * 4  # the all-reduce result
    assert plan["activations"] == 4096
    assert plan["activations_source"] == "xla_temp"
    assert plan["total"] == 16384 + 32768 + 8 + 2048 + 4096 + 64 * 64 * 4


def test_hbm_plan_analytic_fallback():
    a = _artifact(mem_estimate={"activations": 999.0, "total": 5e4})
    plan = memory.hbm_plan(a)
    assert plan["activations"] == 999
    assert plan["activations_source"] == "analytic"


def test_entry_decomposition_trips_on_rot():
    # Claimed state bytes wildly off the module's entry layout.
    a = _artifact(state_bytes={"params": 4}, batch_bytes=0)
    assert _errors(audit_memory(a), "memory.entry_decomposition")


def test_entry_decomposition_clean_on_match():
    a = _artifact(
        state_bytes={"params": 64 * 64 * 4},
        batch_bytes=2 * 8 * 32 * 4 + 8,
    )
    assert not _errors(audit_memory(a), "memory.entry_decomposition")


def test_master_weight_rule_trips_when_told_bf16_over_fp32():
    a = _artifact(
        precision="bf16_mixed",
        stablehlo_text=_SH_BF16,
        state_bytes={"params": 64 * 64 * 4},
        batch_bytes=2 * 8 * 32 * 4 + 8,
    )
    assert _errors(audit_memory(a), "memory.master_weights")


def test_master_weight_rule_accepts_real_bf16_plan():
    # params = bf16 payload (half the masters) + no LN islands here.
    sb = {"params": 64 * 64 * 2, "opt_master": 64 * 64 * 4}
    header = _HLO_HEADER.replace(
        "(f32[64,64]{1,0}, ", "(bf16[64,64]{1,0}, f32[64,64]{1,0}, "
    )
    a = _artifact(
        precision="bf16_mixed",
        stablehlo_text=_SH_BF16,
        hlo_text=header + _HLO_BODY,
        state_bytes=sb,
        batch_bytes=2 * 8 * 32 * 4 + 8,
    )
    assert not _errors(audit_memory(a), "memory.master_weights")


def test_memory_cross_check_warns_when_far_off():
    a = _artifact(
        state_bytes={"params": 64 * 64 * 4},
        batch_bytes=2 * 8 * 32 * 4 + 8,
        mem_estimate={"activations": 0.0, "total": 1e12},
    )
    found = audit_memory(a)
    warns = [f for f in found if f.rule == "memory.bytes_cross_check"]
    assert warns and warns[0].severity == "warn"
    assert not _errors(found)


# --------------------------------------------------------------------------
# family 8: dtype-literal lint
# --------------------------------------------------------------------------

_BAD_OP_SRC = """\
import jax.numpy as jnp

def hot_matmul(x, w):
    # A hard-coded upcast in a hot path: exactly what the lint hunts.
    return (x.astype(jnp.float32) @ w.astype(jnp.float32))

def softmax_island(s):
    return jnp.exp(s.astype(jnp.float32))
"""


def test_dtype_lint_trips_on_unsanctioned_literal():
    sites = dtypelint.lint_source(_BAD_OP_SRC, "fake.py", "ops/fake.py")
    bad = dtypelint.unsanctioned(sites)
    # No allowlist row for ops/fake.py: every literal is unsanctioned.
    assert len(bad) == 3
    assert {s.scope[-1] for s in bad} == {"hot_matmul", "softmax_island"}


def test_dtype_lint_catches_string_dtype_literals():
    """The satellite names `.astype(...)` explicitly: the STRING form
    (`.astype("float32")`, `dtype="bfloat16"`) must trip like the
    attribute form — while bare string comparisons (config plumbing)
    stay out of scope."""
    src = (
        "import jax.numpy as jnp\n"
        "def hot(x):\n"
        "    return x.astype('float32')\n"
        "def alloc(x):\n"
        "    return jnp.zeros_like(x, dtype='bfloat16')\n"
        "def plumbing(cfg):\n"
        "    return cfg.param_dtype == 'float32'\n"
    )
    sites = dtypelint.lint_source(src, "f.py", "ops/f.py")
    assert sorted((s.dtype, s.scope[-1]) for s in sites) == [
        ("bfloat16", "alloc"), ("float32", "hot"),
    ]


def test_audit_artifact_flags_bypass_new_families():
    """audit_graph's --no-numerics/--no-memory must ACTUALLY bypass the
    rule passes, not just their baselines (review finding, this PR)."""
    from dtc_tpu.analysis.rules import audit_artifact

    lied = _artifact(
        precision="bf16_mixed",
        state_dtypes={"params": ["f32"], "opt_moments": ["f32"]},
    )
    assert _errors(audit_artifact(lied), "numerics.")
    assert not _errors(
        audit_artifact(lied, numerics=False), "numerics."
    )
    rot = _artifact(state_bytes={"params": 4}, batch_bytes=0)
    assert _errors(audit_artifact(rot), "memory.")
    assert not _errors(audit_artifact(rot, memory=False), "memory.")


def test_dtype_lint_allowlist_sanctions_scope(monkeypatch):
    monkeypatch.setitem(
        dtypelint.ALLOWLIST, "ops/fake.py", frozenset({"softmax_island"})
    )
    sites = dtypelint.lint_source(_BAD_OP_SRC, "fake.py", "ops/fake.py")
    bad = dtypelint.unsanctioned(sites)
    assert len(bad) == 2 and all(
        s.scope[-1] == "hot_matmul" for s in bad
    )


def test_pristine_tree_lints_clean():
    """The satellite's standing assertion: every hard-coded dtype literal
    in models/ and ops/ sits in a sanctioned mandated-precision scope. A
    new naked literal anywhere else fails THIS test (and the audit
    pre-gate) until allowlisted with a justification."""
    assert audit_dtype_literals() == [], [
        f.message for f in audit_dtype_literals()
    ]
    # And the lint actually sees the tree (a path bug would pass
    # vacuously — same guard as the hostsync lint's non-empty assert).
    assert len(dtypelint.lint_tree()) > 50


def test_allowlist_names_still_exist():
    """Scope names in the allowlist must exist in their files — a
    renamed kernel function would otherwise leave a stale sanction
    behind (the hostsync SANCTIONED_CONDITIONS contract)."""
    import ast

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dtc_tpu")
    for rel, names in dtypelint.ALLOWLIST.items():
        path = os.path.join(pkg, rel)
        assert os.path.exists(path), rel
        tree = ast.parse(open(path).read())
        defined = {
            n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
        }
        for name in names - {"*", "<module>"}:
            assert name in defined, f"{rel}: stale allowlist scope {name!r}"


# --------------------------------------------------------------------------
# baseline sections round-trip
# --------------------------------------------------------------------------

def test_numerics_memory_baseline_sections_roundtrip(tmp_path):
    from dtc_tpu.analysis.report import (
        build_report, check_baselines, write_baselines,
    )

    d = str(tmp_path)
    rep = build_report([_artifact()], [])
    assert "numerics" in rep and "memory" in rep
    paths = write_baselines(rep, d)
    assert {os.path.basename(p) for p in paths} == {
        "train_dp.json", "train_dp.numerics.json", "train_dp.memory.json",
    }
    assert check_baselines(rep, d) == []
    # Numerics-ONLY drift: a state-class dtype changes (the graph and
    # memory fingerprints never read state_dtypes).
    drifted = build_report(
        [_artifact(state_dtypes={"params": ["f32"],
                                 "opt_moments": ["bf16"]})], []
    )
    findings = check_baselines(drifted, d)
    assert {f.artifact for f in findings if f.severity == "error"} == {
        "train_dp.numerics"
    }
    # Memory drift: a state byte moves.
    drifted2 = build_report(
        [_artifact(state_bytes={"params": 16385, "opt_moments": 0,
                                "opt_other": 0})], []
    )
    findings2 = check_baselines(drifted2, d)
    assert {f.artifact for f in findings2 if f.severity == "error"} == {
        "train_dp.memory"
    }


# --------------------------------------------------------------------------
# green path: the real bf16 entry vs its committed baselines
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_green_path_bf16_matches_committed_baseline():
    """Acceptance leg: the REAL bf16_mixed train step lowers through the
    registry, audits clean under every family (bf16 matmuls present, no
    churn, masters fp32, memory plan self-consistent), and matches the
    committed graph + numerics + memory baselines."""
    from dtc_tpu.analysis.lowering import build_train_artifact
    from dtc_tpu.analysis.report import build_report, check_baselines
    from dtc_tpu.analysis.rules import audit_artifact

    art = build_train_artifact("bf16", execute=True)
    findings = audit_artifact(art)
    assert not _errors(findings), [f.message for f in findings]
    dots = numerics.dot_signature_census(art.stablehlo_text)
    assert dots["bf16_bf16"] > 0  # the policy actually lowered
    plan = memory.hbm_plan(art)
    assert plan["opt_master"] > 0
    assert plan["opt_master"] // 2 <= plan["params"] <= plan["opt_master"]
    drift = check_baselines(build_report([art], findings))
    assert not drift, [f.message for f in drift]


@pytest.mark.slow
def test_fp32_program_labeled_bf16_trips_end_to_end():
    """The non-vacuousness proof on the REAL lowering (not a fixture):
    take the committed fp32 dp artifact, relabel it bf16_mixed, and the
    numerics + memory families must both error."""
    from dtc_tpu.analysis.lowering import build_train_artifact

    art = build_train_artifact("dp", execute=False)
    lied = dataclasses.replace(art, precision="bf16_mixed")
    assert _errors(audit_numerics(lied), "numerics.matmul_region")
    assert _errors(audit_memory(lied), "memory.master_weights")
