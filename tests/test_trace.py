"""Flight recorder & end-to-end tracing tests (ISSUE 7): span API,
Perfetto export schema, quantile-histogram parity with the shared
nearest-rank oracle, JSONL rotation, serving-aware shard reduction, the
online SLO monitor, and the flight-recorder dump paths (chaos anomaly,
watchdog fire, SIGTERM) — plus the serving chaos acceptance run whose
trace must show the failing request's full span chain in order."""

import json
import math
import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dtc_tpu.config.schema import (
    ChaosConfig,
    ObsConfig,
    ResilienceConfig,
    ServeConfig,
    SloConfig,
    WatchdogConfig,
)
from dtc_tpu.obs import (
    FlightRecorder,
    JsonlSink,
    MemorySink,
    MetricsRegistry,
    SloMonitor,
    Telemetry,
    Tracer,
    load_flight_dump,
    read_jsonl,
    reduce_shards,
    shard_path,
    to_chrome_trace,
)
from dtc_tpu.obs.registry import HIST_BUCKET_GROWTH, Histogram
from dtc_tpu.utils.percentile import nearest_rank
from tests.conftest import make_train_cfg

VOCAB = 97


# ---------------------------------------------------------------------------
# shared percentile (satellite): the exact oracle
# ---------------------------------------------------------------------------


def test_nearest_rank_edge_cases():
    assert nearest_rank([], 0.5) is None
    assert nearest_rank([7.0], 0.0) == 7.0
    assert nearest_rank([7.0], 0.5) == 7.0
    assert nearest_rank([7.0], 1.0) == 7.0
    assert nearest_rank([3, 1, 2, 4], 0.0) == 1   # q=0 -> min
    assert nearest_rank([3, 1, 2, 4], 1.0) == 4   # q=1 -> max
    assert nearest_rank([1, 2, 3, 4], 0.5) == 2   # ceil(0.5*4)=2nd
    assert nearest_rank([1, 2, 3, 4], 0.51) == 3
    assert nearest_rank(range(1, 101), 0.99) == 99
    with pytest.raises(ValueError):
        nearest_rank([1.0], 1.5)


def test_bench_shares_nearest_rank():
    import bench

    assert bench._pct is nearest_rank


# ---------------------------------------------------------------------------
# quantile histograms (tentpole 3)
# ---------------------------------------------------------------------------


def test_histogram_summary_back_compat_plus_percentiles():
    h = Histogram("t")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    s = h.summary()
    # Existing keys byte-compatible for current consumers...
    assert s["count"] == 3
    assert s["mean"] == pytest.approx(0.2)
    assert s["min"] == 0.1 and s["max"] == 0.3
    assert s["total"] == pytest.approx(0.6)
    # ...plus the quantile keys the SLO questions are phrased in.
    for k in ("p50", "p90", "p99"):
        assert isinstance(s[k], float)
    empty = Histogram("e").summary()
    assert empty["p50"] is None and empty["count"] == 0


def test_histogram_percentiles_within_one_bucket_of_nearest_rank():
    """Parity satellite: bucketed pNN vs the exact nearest-rank oracle on
    identical samples, within one (~10%) bucket width — across scales,
    including zeros."""
    rng = random.Random(7)
    for scale in (1e-4, 1.0, 3e2):
        vals = [rng.lognormvariate(math.log(scale), 1.5) for _ in range(400)]
        h = Histogram("x")
        for v in vals:
            h.observe(v)
        for q in (0.0, 0.5, 0.9, 0.99, 1.0):
            exact = nearest_rank(vals, q)
            got = h.percentile(q)
            assert got is not None
            ratio = got / exact
            assert 1 / HIST_BUCKET_GROWTH <= ratio <= HIST_BUCKET_GROWTH, (
                scale, q, got, exact,
            )
    h = Histogram("z")
    for v in (0.0, 0.0, 0.0, 5.0):
        h.observe(v)
    assert h.percentile(0.5) == 0.0
    assert h.percentile(1.0) == pytest.approx(5.0, rel=0.1)


def test_histogram_reset_drops_warmup_samples():
    h = Histogram("x")
    h.observe(100.0)
    h.reset()
    assert h.count == 0 and h.percentile(0.5) is None
    h.observe(1.0)
    assert h.summary()["count"] == 1 and h.max == 1.0


# ---------------------------------------------------------------------------
# JSONL rotation (satellite)
# ---------------------------------------------------------------------------


def test_jsonl_rotation_segments_and_discovery(tmp_path):
    p = str(tmp_path / "events.r0.jsonl")
    reg = MetricsRegistry()
    reg.add_sink(JsonlSink(p, max_bytes=256))
    for i in range(60):
        reg.emit("step", step=i, step_time_s=0.1)
    reg.close()
    segs = sorted(os.listdir(tmp_path))
    assert "events.r0.jsonl" in segs
    assert "events.r0.jsonl.1" in segs and len(segs) > 3  # actually rotated
    # read_jsonl stitches the segments back in chronological order.
    events = read_jsonl(p)
    assert [e["step"] for e in events] == list(range(60))
    # The reducer sees the whole rotated history as one shard.
    red = reduce_shards(str(tmp_path))
    assert red["hosts"]["0"]["steps"] == 60
    # Rotation keyed per shard: a sibling shard's segments are separate.
    reg2 = MetricsRegistry(process_index=1)
    reg2.add_sink(JsonlSink(str(tmp_path / "events.r1.jsonl")))
    reg2.emit("step", step=0, step_time_s=0.5)
    reg2.close()
    assert reduce_shards(str(tmp_path))["n_hosts"] == 2


def test_jsonl_no_rotation_by_default(tmp_path):
    p = str(tmp_path / "events.r0.jsonl")
    reg = MetricsRegistry()
    reg.add_sink(JsonlSink(p))
    for i in range(50):
        reg.emit("step", step=i)
    reg.close()
    assert os.listdir(tmp_path) == ["events.r0.jsonl"]
    assert len(read_jsonl(p)) == 50


# ---------------------------------------------------------------------------
# serving-aware shard reduction (satellite)
# ---------------------------------------------------------------------------


def _write_shard(obs_dir, proc, events):
    os.makedirs(obs_dir, exist_ok=True)
    with open(shard_path(str(obs_dir), proc), "w") as f:
        for e in events:
            f.write(json.dumps({"proc": proc, **e}) + "\n")


def test_reduce_shards_serving_only(tmp_path):
    """A serving-only run reduces to a typed summary, not silent None."""
    _write_shard(tmp_path, 0, [
        {"etype": "serve_request", "state": "done", "iteration": 9},
        {"etype": "serve_request", "state": "shed", "iteration": 11},
        {"etype": "serve_admit", "iteration": 2},
    ])
    red = reduce_shards(str(tmp_path))
    assert red is not None
    assert red["training_steps"] == 0
    assert red["serve"]["requests"] == 2
    assert red["serve"]["iterations"] == 11
    assert red["serve"]["by_state"] == {"done": 1, "shed": 1}
    assert red["hosts"]["0"]["steps"] == 0
    assert red["hosts"]["0"]["serve_requests"] == 2
    assert red["stragglers"] == [] and red["n_hosts"] == 1


def test_reduce_shards_mixed_training_and_serving(tmp_path):
    """Mixed fleet: step reduction unchanged, serve section added, and
    the serving-only host still appears in the table."""
    _write_shard(tmp_path, 0, [
        {"etype": "step", "step": 1, "step_time_s": 0.1},
        {"etype": "step", "step": 2, "step_time_s": 0.2},
    ])
    _write_shard(tmp_path, 1, [
        {"etype": "serve_request", "state": "done", "iteration": 4},
    ])
    red = reduce_shards(str(tmp_path))
    assert red["hosts"]["0"]["steps"] == 2
    assert red["hosts"]["1"]["steps"] == 0
    assert red["hosts"]["1"]["serve_requests"] == 1
    assert red["serve"]["requests"] == 1
    assert red["n_hosts"] == 2
    assert red["step_time_s"]["mean"] == pytest.approx(0.15)


def test_reduce_shards_empty_still_none(tmp_path):
    _write_shard(tmp_path, 0, [{"etype": "run_start"}])
    assert reduce_shards(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# span API + Perfetto export (tentpole 1)
# ---------------------------------------------------------------------------


def test_tracer_span_context_manager_and_attrs():
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    tr = Tracer(reg, clock=iter([1.0, 3.5]).__next__)
    with tr.span("work", cat="test", foo=1) as sp:
        sp.set(bar="x")
    (e,) = sink.events
    assert e["etype"] == "span" and e["name"] == "work"
    assert e["t0"] == 1.0 and e["dur_s"] == 2.5
    assert e["foo"] == 1 and e["bar"] == "x" and e["ph"] == "X"


def test_tracer_explicit_start_end_cross_scope():
    """The serving pattern: a request span opened at one iteration and
    closed many iterations later, by handle."""
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    t = {"v": 0.0}
    tr = Tracer(reg, clock=lambda: t["v"])
    h = tr.start("req", tid="r1", rid="r1")
    t["v"] = 5.0
    tr.end(h, outcome="done")
    tr.end(h)  # double-end is a no-op
    (e,) = sink.events
    assert e["tid"] == "r1" and e["dur_s"] == 5.0 and e["outcome"] == "done"


def test_tracer_span_records_exception_and_instant():
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    tr = Tracer(reg)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    tr.instant("mark", tid="r1", t=2.0, rid="r1")
    assert sink.events[0]["error"] == "RuntimeError"
    assert sink.events[1]["ph"] == "i" and sink.events[1]["dur_s"] == 0.0


def test_tracer_disabled_is_silent():
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    tr = Tracer(reg, enabled=False)
    with tr.span("a") as sp:
        sp.set(x=1)
    tr.emit_span("b", 0.0, 1.0)
    tr.instant("c")
    assert tr.start("d") is None
    assert sink.events == []


def test_perfetto_export_schema():
    """Acceptance satellite: required keys ph/ts/dur/pid/tid/name on
    every trace event, monotonic ts, instants attached to the owning
    request's track, thread-name metadata present."""
    reg = MetricsRegistry(process_index=2)
    sink = reg.add_sink(MemorySink())
    tr = Tracer(reg, clock=lambda: 0.0)
    tr.emit_span("req.queued", 10.0, 11.0, tid="r1", rid="r1")
    tr.emit_span("req.prefill", 11.0, 11.5, tid="r1", rid="r1")
    tr.emit_span("req.decode", 11.5, 14.0, tid="r1", rid="r1")
    reg.emit("serve_evict", rid="r1", reason="preempted")  # ts-stamped
    tr.instant("req.done", tid="r1", t=14.0, rid="r1")
    out = to_chrome_trace(sink.events)
    rows = [e for e in out["traceEvents"] if e["ph"] != "M"]
    assert len(rows) == 5
    for e in rows:
        for k in ("ph", "ts", "dur", "pid", "tid", "name"):
            assert k in e, e
        assert e["pid"] == 2
    ts = [e["ts"] for e in rows]
    assert ts == sorted(ts) and ts[0] == 0.0  # normalized + monotonic
    # All five share the request track (the evict instant has no tid
    # field — its rid routes it), and metadata names the track.
    assert len({e["tid"] for e in rows}) == 1
    meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
    assert any(m["args"]["name"] == "r1" for m in meta)
    xs = [e for e in rows if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["req.queued", "req.prefill", "req.decode"]
    assert xs[0]["dur"] == pytest.approx(1e6)


# ---------------------------------------------------------------------------
# flight recorder (tentpole 2)
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bound_and_dump(tmp_path):
    reg = MetricsRegistry()
    rec = reg.add_sink(FlightRecorder(capacity=8))
    for i in range(30):
        reg.emit("step", step=i)
    assert len(rec.events) == 8
    assert [e["step"] for e in rec.events] == list(range(22, 30))
    path = rec.dump(str(tmp_path / "flight.json"), reason="test", step=29)
    body = load_flight_dump(path)
    assert body["reason"] == "test" and body["step"] == 29
    assert body["n_events"] == 8
    assert body["events"][-1]["step"] == 29  # last event = failing step
    assert not [p for p in os.listdir(tmp_path) if ".tmp" in p]  # atomic


def test_warmupless_first_step_emits_one_compile_span(tmp_path):
    """A warmup-less first step's cold compile drains through the
    startup path; the step-span synthesis must NOT emit a second
    'compile' span for the same seconds (the attribution table sums per
    name). A steady-state recompile still gets its own span."""
    import jax.numpy as jnp

    tele = Telemetry(output_dir=str(tmp_path))
    try:
        tele.on_step_start(1)
        jax.jit(lambda v: v * 2 + tmp_path.stat().st_mode)(jnp.ones(3)).block_until_ready()
        tele.on_step_end(1, elapsed_s=0.1, synced=True)
        tele.on_step_start(2)
        jax.jit(lambda v: v * 3 - 1)(jnp.ones((2, 2))).block_until_ready()
        tele.on_step_end(2, elapsed_s=0.2, synced=True)
        tele.flush()
    finally:
        tele.close()
    events = read_jsonl(str(tmp_path / "obs" / "events.r0.jsonl"))
    compile_spans = [e for e in events
                     if e["etype"] == "span" and e["name"] == "compile"]
    assert [e["step"] for e in compile_spans] == [0, 2]
    assert compile_spans[1].get("recompile") is True


def test_telemetry_dump_on_anomaly_and_hung_step(tmp_path):
    tele = Telemetry(output_dir=str(tmp_path))
    try:
        tele.on_step_start(1)
        tele.on_step_end(1, elapsed_s=0.1, synced=True)
        tele.on_anomaly(1, reason="non-finite loss", action="warn")
        p = os.path.join(str(tmp_path), "obs", "flight.r0.json")
        body = load_flight_dump(p)
        assert body["reason"].startswith("anomaly")
        assert any(e["etype"] == "anomaly" for e in body["events"])
        # The per-step spans made it into the ring before the trip.
        assert any(e["etype"] == "span" and e["name"] == "step"
                   for e in body["events"])
        tele.on_hung_step(2, duration_s=9.9)
        assert load_flight_dump(p)["reason"] == "hung_step"
    finally:
        tele.close()


# ---------------------------------------------------------------------------
# SLO monitor (tentpole 4)
# ---------------------------------------------------------------------------


def test_slo_config_validation():
    with pytest.raises(ValueError):
        SloConfig(window=1)
    with pytest.raises(ValueError):
        SloConfig(check_every=0)
    with pytest.raises(ValueError):
        SloConfig(ttft_p99_s=-1.0)
    with pytest.raises(ValueError):
        SloConfig(shed_rate=1.5)


def test_slo_monitor_edge_triggered_breach_and_recovery():
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    mon = SloMonitor.from_config(
        SloConfig(ttft_p99_s=0.5, window=8, min_samples=2), reg,
        runtime="serve",
    )
    assert mon is not None
    mon.observe("serve_ttft_s", 0.9)
    mon.observe("serve_ttft_s", 0.95)
    assert mon.evaluate(iteration=1) and mon.degrade_active
    mon.evaluate(iteration=2)  # still breaching: NO second breach event
    breaches = [e for e in sink.events if e["etype"] == "slo_breach"]
    assert len(breaches) == 1
    b = breaches[0]
    assert b["objective"] == "ttft_p99_s" and b["value"] > b["threshold"]
    assert b["iteration"] == 1
    assert reg.snapshot()["slo_breaches"] == 1
    for _ in range(8):
        mon.observe("serve_ttft_s", 0.01)
    assert not mon.evaluate(iteration=3) and not mon.degrade_active
    assert [e["etype"] for e in sink.events][-1] == "slo_recovered"


def test_slo_monitor_rate_objective_and_off_by_default():
    reg = MetricsRegistry()
    sink = reg.add_sink(MemorySink())
    assert SloMonitor.from_config(SloConfig(), reg) is None  # all off
    assert SloMonitor.from_config(None, reg) is None
    mon = SloMonitor.from_config(
        SloConfig(shed_rate=0.25, window=8, min_samples=4), reg,
        runtime="serve",
    )
    for bad in (True, True, False, False):
        mon.observe_outcome("serve_outcome_shed", bad)
    (b,) = mon.evaluate(iteration=5)
    assert b["kind"] == "rate" and b["value"] == 0.5
    # A rate breach alone must NOT activate latency degradation.
    assert not mon.degrade_active
    assert [e for e in sink.events if e["etype"] == "slo_breach"]


# ---------------------------------------------------------------------------
# serving integration: spans, SLO wiring, chaos acceptance
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_model():
    from dtc_tpu.config.schema import ModelConfig
    from dtc_tpu.models.gpt import GPT

    cfg = ModelConfig(
        vocab_size=VOCAB, d_model=64, n_layers=4, n_heads=4, d_ff=128,
        max_seq_len=32, dropout=0.0, param_dtype="float32",
        compute_dtype="float32", attention="dense",
    )
    model = GPT(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.ones((1, 1), jnp.int32),
        train=False,
    )["params"]
    return model, params


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=n).tolist() for n in sizes]


def test_serve_clean_run_waterfall_matches_slo_timings(served_model):
    """Acceptance (clean leg): every completed request shows a full
    queued→prefill→decode chain whose span edges reproduce the
    TTFT/queue-wait the registry histograms observed — same clock, same
    numbers."""
    from dtc_tpu.serve import Request, RequestState, ServingEngine

    model, params = served_model
    eng = ServingEngine(model, params, ServeConfig(
        slots=2, page_size=4, queue_depth=8, max_new_tokens=4,
        prefill_bucket=8,
    ))
    sink = eng.reg.add_sink(MemorySink())
    for i, p in enumerate(_prompts(0, [5, 7, 6])):
        eng.submit(Request(rid=f"r{i}", prompt=p, max_new_tokens=4))
    res = eng.run(max_steps=200)
    assert all(r.state is RequestState.DONE for r in res.values())

    spans = [e for e in sink.events if e["etype"] == "span"]
    by_rid = {}
    for e in spans:
        if "rid" in e:
            by_rid.setdefault(e["rid"], []).append(e)
    ttfts, qwaits = [], []
    for rid, r in res.items():
        mine = {e["name"]: e for e in by_rid[rid]}
        assert {"req.queued", "req.prefill", "req.decode", "req.done"} <= set(mine)
        queued, prefill = mine["req.queued"], mine["req.prefill"]
        # Span-derived SLO numbers == the engine's own (one clock).
        ttft = prefill["t0"] + prefill["dur_s"] - queued["t0"]
        qwait = queued["dur_s"]
        assert ttft == pytest.approx(r.ttft_s, abs=1e-4)
        # queue wait ends at admission START; the engine stamps
        # admitted_t after the prefill returns, so the span's queue wait
        # plus the prefill duration is the recorded queue_wait_s.
        assert qwait + prefill["dur_s"] == pytest.approx(
            r.queue_wait_s, abs=1e-4
        )
        assert mine["req.decode"]["n_tokens"] == len(r.tokens)
        ttfts.append(r.ttft_s)
        qwaits.append(r.queue_wait_s)
    # Registry-histogram percentiles match nearest-rank on the same
    # population to within one bucket.
    h50 = eng.reg.histogram("serve_ttft_s").percentile(0.5)
    exact = nearest_rank(ttfts, 0.5)
    assert h50 == pytest.approx(exact, rel=HIST_BUCKET_GROWTH - 1 + 1e-6)
    # decode_step scheduler spans exist, one per working iteration.
    assert any(e["name"] == "decode_step" for e in spans)


def test_serve_chaos_acceptance_dump_and_ordered_trace(served_model, tmp_path):
    """ISSUE 7 acceptance: serve preemption + poisoned logits (+ a tight
    TTFT SLO) yield (a) a flight-recorder dump, (b) a Perfetto-loadable
    trace where the preempted request's chain queued→prefill→evict→
    requeued→prefill→decode→done is present and ordered, and (c)
    slo_breach + recovery events in the same stream."""
    from dtc_tpu.serve import Request, RequestState, ServingEngine

    model, params = served_model
    tele = Telemetry.for_serving(str(tmp_path))
    scfg = ServeConfig(
        slots=1, page_size=4, queue_depth=8, max_new_tokens=6,
        prefill_bucket=8,
        chaos=ChaosConfig(
            enabled=True, serve_preempt_at_step=2,
            serve_poison_logits_at_step=4,
        ),
        slo=SloConfig(ttft_p99_s=1e-9, window=8, min_samples=1,
                      check_every=1),
        # Watchdog off so the LAST flight dump is deterministically the
        # chaos one (a retry-slowed iteration could otherwise flag).
        watchdog=WatchdogConfig(enabled=False),
    )
    eng = ServingEngine(model, params, scfg, telemetry=tele)
    for i, p in enumerate(_prompts(1, [5, 6])):
        eng.submit(Request(rid=f"c{i}", prompt=p, max_new_tokens=6))
    res = eng.run(max_steps=300)
    tele.flush()
    assert all(r.state is RequestState.DONE for r in res.values())
    snap = eng.reg.snapshot()
    assert snap["serve_preemptions"] == 1 and snap["chaos_injections"] == 2
    assert snap["serve_retries"] >= 1
    assert snap["slo_breaches"] >= 1
    victim = next(rid for rid, r in res.items() if r.n_evictions == 1)

    # (a) the chaos run dumped a flight record with the chaos evidence.
    dump = load_flight_dump(str(tmp_path / "obs" / "flight.r0.json"))
    assert dump["reason"].startswith("chaos:")
    assert any(e["etype"] == "chaos" for e in dump["events"])

    tele.close()
    events = read_jsonl(str(tmp_path / "obs" / "events.r0.jsonl"))
    etypes = {e["etype"] for e in events}
    assert {"span", "chaos", "serve_evict", "slo_breach", "recovery"} <= etypes

    # (b) the victim's chain, ordered: two queued/prefill pairs around
    # the evict mark, decode after the first token, terminal last.
    mine = [
        e for e in events
        if e.get("rid") == victim and (
            e["etype"] == "span" or e["etype"] == "serve_evict"
        )
    ]
    mine.sort(key=lambda e: e.get("t0", e.get("ts")))
    names = [e.get("name", e["etype"]) for e in mine]
    assert names.count("req.queued") == 2 and names.count("req.prefill") == 2
    assert names.index("req.queued") < names.index("serve_evict")
    assert names[-1] == "req.done"
    assert names.index("serve_evict") < len(names) - 1 - names[::-1].index(
        "req.prefill"
    ), "re-prefill must follow the eviction"
    assert "req.decode" in names

    # (c) Perfetto export of the whole run loads with monotonic ts and
    # carries the breach + chaos instants.
    out = to_chrome_trace(events)
    rows = [e for e in out["traceEvents"] if e["ph"] != "M"]
    ts = [e["ts"] for e in rows]
    assert ts == sorted(ts)
    row_names = {e["name"] for e in rows}
    assert "slo_breach" in row_names and "chaos" in row_names
    assert {"req.queued", "req.prefill", "req.decode"} <= row_names


def test_serve_watchdog_fire_dumps_flight(served_model, tmp_path):
    """Satellite dump path: a chaos scheduler stall trips the serving
    watchdog; the dump is loadable and its last decode_step span is the
    flagged iteration's."""
    from dtc_tpu.serve import Request, ServingEngine

    model, params = served_model
    tele = Telemetry.for_serving(str(tmp_path))
    eng = ServingEngine(model, params, ServeConfig(
        slots=1, page_size=4, queue_depth=4, max_new_tokens=10,
        prefill_bucket=8,
        watchdog=WatchdogConfig(enabled=True, factor=4.0, min_samples=3),
        chaos=ChaosConfig(enabled=True, serve_stall_at_step=8, stall_s=1.0),
    ), telemetry=tele)
    eng.submit(Request(rid="w", prompt=_prompts(2, [6])[0], max_new_tokens=10))
    eng.run(max_steps=100)
    tele.flush()
    assert eng.reg.snapshot().get("serve_hung_steps", 0) >= 1
    dump = load_flight_dump(str(tmp_path / "obs" / "flight.r0.json"))
    assert dump["reason"] == "hung_step"
    flagged = dump["iteration"]
    dsteps = [e for e in dump["events"]
              if e.get("etype") == "span" and e.get("name") == "decode_step"]
    assert dsteps and dsteps[-1]["iteration"] == flagged
    tele.close()


def test_serve_slo_breach_activates_degrade(served_model):
    """The scheduler reacts to the monitor: with a breaching latency SLO
    and degrade enabled, new admissions get the degraded token cap even
    though the queue watermark was never crossed."""
    from dtc_tpu.serve import Request, RequestState, ServingEngine

    model, params = served_model
    eng = ServingEngine(model, params, ServeConfig(
        slots=1, page_size=4, queue_depth=16, max_new_tokens=8,
        prefill_bucket=8, degrade_watermark=0.0, degrade_max_new_tokens=2,
        slo=SloConfig(ttft_p99_s=1e-9, window=8, min_samples=1,
                      check_every=1),
    ))
    p0, p1 = _prompts(3, [5, 6])
    eng.submit(Request(rid="a", prompt=p0, max_new_tokens=8))
    eng.run(max_steps=100)
    assert not eng.results["a"].degraded  # no samples yet at its admission
    eng.submit(Request(rid="b", prompt=p1, max_new_tokens=8))
    res = eng.run(max_steps=100)
    assert res["b"].state is RequestState.DONE
    assert res["b"].degraded and len(res["b"].tokens) == 2
    assert eng.reg.snapshot()["serve_degraded"] == 1


# ---------------------------------------------------------------------------
# trainer integration: spans in the shard, dumps on chaos paths
# ---------------------------------------------------------------------------


def test_trainer_emits_step_spans_and_slo_breach(tiny_model_cfg, opt_cfg, tmp_path):
    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "dp", steps=3, log_every=1, output_dir=str(tmp_path),
        warmup_steps=1,
        slo=SloConfig(step_time_p99_s=1e-9, window=8, min_samples=1,
                      check_every=1),
    )
    train(cfg, tiny_model_cfg, opt_cfg)
    events = read_jsonl(str(tmp_path / "obs" / "events.r0.jsonl"))
    spans = [e for e in events if e["etype"] == "span"]
    steps = [e for e in spans if e["name"] == "step"]
    assert [e["step"] for e in steps] == [1, 2, 3]
    by_step = {e["step"]: e for e in events if e["etype"] == "step"}
    for e in steps:
        # Span duration == the step event's measured step time.
        assert e["dur_s"] == pytest.approx(
            by_step[e["step"]]["step_time_s"], abs=2e-6
        )
    assert any(e["name"] == "dispatch" for e in spans)
    # An impossible step-time objective breached online, during the run.
    assert any(e["etype"] == "slo_breach" for e in events)


def test_trainer_trace_off_emits_no_spans(tiny_model_cfg, opt_cfg, tmp_path):
    from dataclasses import replace

    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg("dp", steps=2, output_dir=str(tmp_path))
    cfg = replace(cfg, obs=replace(cfg.obs, trace=False))
    train(cfg, tiny_model_cfg, opt_cfg)
    events = read_jsonl(str(tmp_path / "obs" / "events.r0.jsonl"))
    assert events and not [e for e in events if e["etype"] == "span"]


def test_trainer_chaos_nan_anomaly_dumps_flight(tiny_model_cfg, opt_cfg, tmp_path):
    """Satellite dump path: a chaos NaN poison trips the anomaly guard
    (no checkpoint -> warn) and the dump's timeline ends at the failing
    step."""
    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "dp", steps=2, log_every=2, output_dir=str(tmp_path),
        resilience=ResilienceConfig(
            chaos=ChaosConfig(enabled=True, nan_at_step=2),
        ),
    )
    train(cfg, tiny_model_cfg, opt_cfg)
    dump = load_flight_dump(str(tmp_path / "obs" / "flight.r0.json"))
    assert dump["reason"].startswith("anomaly: non-finite loss")
    assert dump["step"] == 2
    anomalies = [e for e in dump["events"] if e["etype"] == "anomaly"]
    assert anomalies and anomalies[-1]["step"] == 2
    step_spans = [e for e in dump["events"]
                  if e["etype"] == "span" and e["name"] == "step"]
    assert step_spans and step_spans[-1]["step"] == 2  # last span = failing step


def test_trainer_chaos_sigterm_dumps_flight(tiny_model_cfg, opt_cfg, tmp_path):
    """Satellite dump path: simulated preemption (real SIGTERM through
    the real handler) leaves a dump before the graceful stop."""
    from dtc_tpu.train.trainer import train

    cfg = make_train_cfg(
        "dp", steps=6, log_every=2, output_dir=str(tmp_path),
        checkpoint_every=2,
        resilience=ResilienceConfig(
            chaos=ChaosConfig(enabled=True, sigterm_at_step=3),
        ),
    )
    res = train(cfg, tiny_model_cfg, opt_cfg)
    assert len(res.losses) == 3  # stopped at the preemption step
    dump = load_flight_dump(str(tmp_path / "obs" / "flight.r0.json"))
    assert dump["reason"] == "sigterm" and dump["step"] == 3
    assert any(e["etype"] == "chaos" and e.get("kind") == "sigterm"
               for e in dump["events"])


# ---------------------------------------------------------------------------
# trace_report (offline leg)
# ---------------------------------------------------------------------------


def test_trace_report_table_waterfall_compare(tmp_path, capsys):
    from scripts.trace_report import (
        compare_runs, load_events, request_waterfalls, span_table,
    )

    def fake_run(d, scale):
        os.makedirs(d)
        reg = MetricsRegistry()
        reg.add_sink(JsonlSink(os.path.join(d, "events.r0.jsonl")))
        tr = Tracer(reg, clock=lambda: 0.0)
        t = 0.0
        for step in range(4):
            tr.emit_span("step", t, t + scale, cat="train", step=step)
            t += scale
        tr.emit_span("req.queued", t, t + 1, tid="q1", rid="q1")
        tr.emit_span("req.prefill", t + 1, t + 2, tid="q1", rid="q1")
        reg.emit("serve_evict", rid="q1", reason="preempted")
        reg.close()

    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    fake_run(a, 0.1)
    fake_run(b, 0.2)
    ev = load_events(a)
    table = span_table(ev)
    step_row = next(r for r in table if r["name"] == "step")
    assert step_row["count"] == 4
    assert step_row["p50_s"] == pytest.approx(0.1)
    falls = request_waterfalls(ev)
    assert "q1" in falls
    assert [x["name"] for x in falls["q1"]][:2] == ["req.queued", "req.prefill"]
    assert any(x["name"].startswith("serve_evict") for x in falls["q1"])
    rows = compare_runs(ev, load_events(b))
    step_cmp = next(r for r in rows if r["name"] == "train/step")
    assert step_cmp["p50_delta_pct"] == pytest.approx(100.0, abs=1.0)


def test_trace_report_resolves_obs_subdir(tmp_path):
    from scripts.trace_report import load_events

    obs = tmp_path / "run" / "obs"
    os.makedirs(obs)
    reg = MetricsRegistry()
    reg.add_sink(JsonlSink(str(obs / "events.r0.jsonl")))
    reg.emit("run_start")
    reg.close()
    assert load_events(str(tmp_path / "run"))[0]["etype"] == "run_start"
    assert load_events(str(obs))[0]["etype"] == "run_start"
    with pytest.raises(SystemExit):
        load_events(str(tmp_path / "empty"))
